"""The TPUJob controller: level-triggered reconcile of TPUJob -> gang of
pods/services (SURVEY.md C15 + C18 joined, re-designed for TPU gang
semantics).

Reconcile contract (idempotent; every step safe to repeat — SURVEY.md §7
hard part 2):

1. key -> cache lookup; a missing object means 'deleted' -> release the
   gang (k8s-operator.md:162-164).
2. ``deletion_timestamp`` set -> finalizer logic: tear down replicas,
   release slices, strip the finalizer so the store completes the delete
   (k8s-operator.md:36-43; SURVEY.md §3.4).
3. default + validate; invalid specs -> Failed(ValidationFailed).
4. finished jobs -> clean-pod policy + TTL; completed pods are *kept*
   unless policy says otherwise (k8s-operator.md:50-52).
5. gang admission (all-or-nothing, SURVEY.md §7 hard part 1); short
   capacity -> requeue with event, optional admission timeout -> Failed.
6. create missing pods/services (level-triggered: compares desired vs
   observed, never assumes its own last write survived).
7. failure handling (k8s-operator.md:47-49 translated to slices):
   - gang mode (TPU): any failed pod -> whole-gang restart-from-checkpoint
     while ``backoff_limit`` lasts, then Failed;
   - per-pod mode (cpu/hermetic, gang=False): OnFailure/Always restart the
     task in place up to ``max_restarts``; Never -> replacement pods are
     NOT created, job fails (the reference's Never-vs-OnFailure split).
8. status: replica counts, Created/Running/Succeeded/Failed conditions,
   ``active_deadline_seconds`` enforcement.
"""

from __future__ import annotations

import re
import time
from typing import List, Optional, Tuple

from tfk8s_tpu.api import helpers, serde, set_defaults, validate
from tfk8s_tpu.api.types import (
    CleanPodPolicy,
    JobConditionType,
    Pod,
    PodPhase,
    ReplicaStatus,
    ReplicaType,
    RestartPolicy,
    TPUJob,
)
from tfk8s_tpu.client.clientset import Clientset
from tfk8s_tpu.client.informer import SharedIndexInformer, ResourceEventHandler
from tfk8s_tpu.client.listers import Lister
from tfk8s_tpu.client.store import AlreadyExists, Conflict, NotFound
from tfk8s_tpu.controller.controller import Controller
from tfk8s_tpu.obs.trace import TRACEPARENT_ENV, Tracer, get_tracer
from tfk8s_tpu.trainer import labels as L
from tfk8s_tpu.trainer import replicas as R
from tfk8s_tpu.trainer.gang import SliceAllocator
from tfk8s_tpu.utils.logging import EventRecorder, Metrics, get_logger

log = get_logger("tpujob")

FINALIZER = "tfk8s.dev/job-cleanup"
RESTARTS_ANNOTATION = "tfk8s.dev/restarts"
PENDING_REQUEUE_S = 0.5

# Env keys derived from per-sync controller state rather than the job
# spec — the SliceAllocator's in-memory placement and the creating
# sync's trace context; excluded from the stale-render diff in
# _reconcile_replicas so an operator restart (fresh placement, fresh
# trace ids) doesn't churn running gangs.
_PLACEMENT_ENV_KEYS = frozenset(
    {"TFK8S_SLICE_ID", "TFK8S_HOST_INDEX", TRACEPARENT_ENV}
)

# Training-progress keys mirrored from pod status into per-job labeled
# gauges on /metrics (runtime/progress.py -> runtime/kubelet.py -> here).
_TRAINING_GAUGE_KEYS = (
    "steps_per_sec",
    "examples_per_sec",
    "step",
    "compile_seconds",
    "input_mb_per_sec",
    "input_wait_seconds",
    "input_starved_steps",
    # the decode pool's delivered rate (image input) — the series the
    # image-input-ceiling operator alert watches, next to input MB/s
    "decoded_images_per_sec",
)

# Node-lost detection (k8s node-lease semantics): a RUNNING pod whose
# node's heartbeat Lease (runtime/kubelet.py NODE_LEASE_PREFIX) has been
# stale for GRACE x lease_duration is marked Failed(NodeLost), feeding
# the ordinary failure path (gang restart-from-checkpoint). Nodes that
# never wrote a lease are exempt — there is no liveness contract to
# break. Jobs with running pods are re-checked every CHECK_PERIOD
# because a dead node emits no events to wake the reconciler.
NODE_LOST_GRACE = 2.0
NODE_CHECK_PERIOD_S = 2.0

# Elastic resize (RunPolicy.elastic): grace the controller gives the
# surviving pods to finish their in-flight step and commit a drain
# checkpoint before the world re-forms at the new size. Past the
# deadline a still-running old-world pod is hard-deleted (the
# SIGTERM->SIGKILL escalation). Tests shrink this via monkeypatch.
RESIZE_DRAIN_GRACE_S = 5.0
RESIZE_POLL_S = 0.1


def _contract_env(pod) -> dict:
    return {
        k: v
        for k, v in pod.spec.containers[0].env.items()
        if k not in _PLACEMENT_ENV_KEYS
    }


class TPUJobController:
    """Owns the TPUJob/Pod/Service informers and the reconcile logic."""

    def __init__(
        self,
        clientset: Clientset,
        allocator: Optional[SliceAllocator] = None,
        recorder: Optional[EventRecorder] = None,
        metrics: Optional[Metrics] = None,
        resync_period: float = 0.0,
        tracer: Optional[Tracer] = None,
    ):
        self.cs = clientset
        self.allocator = allocator or SliceAllocator()
        # default recorder mirrors events into the cluster as Event
        # objects (utils/logging.py EventRecorder sink) so `describe` /
        # `get --kind events` work across the apiserver
        self.recorder = recorder or EventRecorder(sink=clientset)
        self.metrics = metrics or Metrics()
        self.tracer = tracer or get_tracer()

        self.job_informer = SharedIndexInformer(
            clientset.tpujobs(namespace=None), resync_period, name="tpujob",
            metrics=self.metrics,
        )
        self.pod_informer = SharedIndexInformer(
            clientset.pods(namespace=None), resync_period, name="pod",
            metrics=self.metrics,
        )
        self.svc_informer = SharedIndexInformer(
            clientset.services(namespace=None), resync_period, name="service",
            metrics=self.metrics,
        )
        self.jobs = Lister(self.job_informer.indexer, "TPUJob")
        self.pods = Lister(self.pod_informer.indexer, "Pod")
        self.services = Lister(self.svc_informer.indexer, "Service")

        self.controller = Controller(
            "tpujob",
            self.sync,
            informers=[self.job_informer, self.pod_informer, self.svc_informer],
            recorder=self.recorder,
            metrics=self.metrics,
            kind="TPUJob",
            tracer=self.tracer,
        )
        self.job_informer.add_event_handler(self.controller.default_handler())
        # Pod/Service events reconcile their owning job (the enqueuePod
        # pattern of k8s-operator.md:132-139, re-keyed to the owner) —
        # with the reference's update filter (k8s-operator.md:142-150):
        # a pod update that only refreshed status.log_tail (the kubelet's
        # periodic log flush) changes nothing a reconcile acts on, and
        # enqueueing it would cost one full job sync per chatty pod per
        # flush interval.
        self.pod_informer.add_event_handler(ResourceEventHandler(
            on_add=self._enqueue_owner,
            on_update=self._pod_updated,
            on_delete=self._enqueue_owner,
        ))
        self.svc_informer.add_event_handler(ResourceEventHandler(
            on_add=self._enqueue_owner,
            on_update=lambda old, new: self._enqueue_owner(new),
            on_delete=self._enqueue_owner,
        ))
        for mname, help_text in (
            ("tfk8s_status_patches_skipped_total",
             "Status writes skipped because the computed status deep-"
             "compared equal to the cached server state."),
            ("tpujob.pods_created_total", "Pods created by the reconciler."),
            ("tpujob.pods_deleted_total", "Pods deleted by the reconciler."),
            ("tpujob.gang_restarts_total", "Whole-gang restarts from checkpoint."),
            ("tpujob.gang_pending_total", "Syncs that found no gang capacity."),
            ("tpujob.succeeded_total", "Jobs that reached Succeeded."),
            ("tpujob.preemptions_total", "Gangs evicted for higher priority."),
            ("tpujob.suspensions_total", "Gangs parked by RunPolicy.suspend."),
            ("tpujob.node_lost_pods_total", "Running pods failed via stale node lease."),
            ("tfk8s_elastic_resizes_total",
             "Elastic gang resizes, labeled by direction (up/down)."),
            ("tfk8s_drain_checkpoint_seconds",
             "Drain-checkpoint commit latency reported by reclaimed workers."),
            ("tpujob.recovery_seconds",
             "Seconds from resize start to the resized gang Running."),
            ("gang.free_slices", "Free whole slices per accelerator type."),
            ("tpujob.training.steps_per_sec", "Per-job reported training step rate."),
            ("tpujob.training.step_seconds", "Per-job distribution of step wall time."),
            ("tpujob.training.compile_seconds", "Per-job first-step compile time."),
            ("tpujob.training.input_starved_steps", "Per-job steps that waited on input."),
        ):
            self.metrics.describe(mname, help_text)
        # gang release needs the uid after the job object is gone
        self._uid_by_key: dict = {}
        # pod name -> restart count to stamp on the next recreation
        self._pending_restart_counts: dict = {}
        # evaluator pod uids whose terminal failure was already recorded
        # (their Failed pods persist, re-observed by every reconcile)
        self._evaluator_failures_seen: set = set()
        # job key -> gang_restarts floor: the recreate sync can run off a
        # stale cached job whose status predates the increment write; a
        # pod rendered with the old TFK8S_GANG_RESTARTS would repeat the
        # pre-restart run and burn a second unit of backoff_limit
        self._gang_restarts_floor: dict = {}
        # same stale-cache protection for the preemption counter
        self._preemptions_floor: dict = {}
        # job key -> (world_version, elastic_replicas) floor: a resize's
        # status write may not be in the informer cache yet; rendering
        # off the pre-resize world would recreate the OLD gang size
        self._elastic_floor: dict = {}
        # job key -> (start time, direction) of the resize in flight —
        # closed into the per-job recovery_seconds gauge and the
        # job.resize trace span when the resized gang reaches Running
        self._resize_started: dict = {}
        # job key -> wall time of the last resize (scale-up debounce)
        self._last_resize: dict = {}

    def _enqueue_owner(self, obj) -> None:
        meta = getattr(obj, "obj", obj).metadata  # unwrap DeletedFinalStateUnknown
        job_name = meta.labels.get(L.JOB_NAME)
        if job_name:
            self.controller.enqueue_key(f"{meta.namespace}/{job_name}")

    def _pod_updated(self, old: Pod, new: Pod) -> None:
        # Mirror reported training progress into per-job /metrics series
        # (VERDICT r2 next #8): a running job's step rate and throughput
        # are operator-visible without touching the reconcile path.
        if new.status.training and new.status.training != old.status.training:
            job = new.metadata.labels.get(L.JOB_NAME)
            # only for a LIVE owner: a late pod update delivered after
            # _finalize pruned the job's series must not resurrect them
            # (the job is gone or carries its deletion timestamp by then)
            owner = None
            if job:
                owner = self.jobs.get_by_key(f"{new.metadata.namespace}/{job}")
            if owner is not None and owner.metadata.deletion_timestamp is None:
                # LABELED series (one name, per-job label set): deletion
                # GCs exactly this job's series via remove_labels —
                # metric names stay fixed as jobs come and go
                job_labels = {"namespace": new.metadata.namespace, "job": job}
                for k in _TRAINING_GAUGE_KEYS:
                    if k in new.status.training:
                        self.metrics.set_gauge(
                            f"tpujob.training.{k}",
                            new.status.training[k],
                            job_labels,
                        )
                if "step_seconds" in new.status.training:
                    self.metrics.observe(
                        "tpujob.training.step_seconds",
                        new.status.training["step_seconds"],
                        job_labels,
                    )
                # a reclaimed worker reports its drain-checkpoint commit
                # latency exactly once per drain (runtime/train.py) —
                # mirror it into the operator histogram
                drain_s = new.status.training.get("drain_checkpoint_seconds")
                if drain_s is not None and drain_s != old.status.training.get(
                    "drain_checkpoint_seconds"
                ):
                    self.metrics.observe(
                        "tfk8s_drain_checkpoint_seconds", drain_s, job_labels
                    )
        if (
            old.metadata.resource_version != new.metadata.resource_version
            and old.metadata.uid == new.metadata.uid
            and old.metadata.deletion_timestamp == new.metadata.deletion_timestamp
            and old.status.phase == new.status.phase
            and old.status.exit_code == new.status.exit_code
            and old.status.message == new.status.message
            and old.status.restarts == new.status.restarts
            and old.status.host == new.status.host
            and old.spec == new.spec
            and (
                old.status.log_tail != new.status.log_tail
                or old.status.training != new.status.training
            )
        ):
            return  # log-flush/progress-only refresh; nothing to reconcile
        self._enqueue_owner(new)

    def run(self, workers: Optional[int] = None, stop=None, block: bool = True) -> bool:
        from tfk8s_tpu.controller.controller import DEFAULT_SYNC_WORKERS

        return self.controller.run(
            DEFAULT_SYNC_WORKERS if workers is None else workers, stop, block=block
        )

    # ------------------------------------------------------------------ sync

    def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        with self.tracer.start_span("lister.get", attributes={"key": key}):
            job = self.jobs.get_by_key(key)
        if job is None:
            # Object gone from cache: release any gang it held
            uid = self._uid_by_key.pop(key, None)
            if uid:
                self.allocator.release(uid)
                self._export_capacity_gauges()
            self._prune_job_state(key)
            return

        if job.metadata.deletion_timestamp is not None:
            self._finalize(job)
            return

        # The lister returned the SHARED frozen cached instance; roundtrip
        # gives this sync a private mutable copy to default and edit.
        cached_status_wire = serde.to_wire(job.status)
        job = set_defaults(serde.roundtrip(job))  # work on a defaulted copy
        # Baseline for the status-write skip (_write_status): the status
        # the server currently holds (as cached). A computed status that
        # deep-compares equal means the patch round trip would be a
        # no-op — skip it and count the skip.
        job._status_baseline = cached_status_wire
        errs = validate(job)
        if errs:
            if helpers.set_condition(
                job.status,
                JobConditionType.FAILED,
                reason="ValidationFailed",
                message="; ".join(errs),
            ):
                self.recorder.event("TPUJob", key, "ValidationFailed", "; ".join(errs))
                self._write_status(job)
            # A job can become invalid *after* admission (spec edited while
            # running): still tear down and release its slices.
            self._cleanup_finished(job)
            return

        self._uid_by_key[key] = job.metadata.uid

        if helpers.is_finished(job.status):
            self._cleanup_finished(job)
            return

        # Ensure our finalizer before creating anything it must clean up.
        # The finalizers LIST replaces wholesale under merge-patch, so the
        # write carries an rv precondition: without it a finalizer some
        # other writer added concurrently would be silently clobbered.
        if FINALIZER not in job.metadata.finalizers:
            try:
                self.cs.tpujobs(ns).patch(
                    job.metadata.name,
                    {"metadata": {
                        "resourceVersion": str(job.metadata.resource_version),
                        "finalizers": job.metadata.finalizers + [FINALIZER],
                    }},
                )
            except Conflict:
                self.controller.enqueue_key(job.metadata.key)
            return  # patched object re-enqueues via the watch

        changed = helpers.set_condition(
            job.status, JobConditionType.CREATED, reason="JobCreated"
        )
        if changed:
            self.recorder.event("TPUJob", key, "JobCreated")

        # Kueue-style suspend (RunPolicy.suspend): evict the gang, free
        # the slices, park the job until the flag clears — then the
        # ordinary admission path below re-admits and the eviction
        # counter makes the relaunched gang resume from checkpoint.
        if job.spec.run_policy.suspend:
            self._suspend(job)
            return
        sus = helpers.get_condition(job.status, JobConditionType.SUSPENDED)
        if sus is not None and sus.status:
            sus.status = False
            # restart the admission clock: a job parked (possibly since
            # birth) for days must not be insta-failed AdmissionTimeout
            # measured against its CREATED transition
            created_cond = helpers.get_condition(
                job.status, JobConditionType.CREATED
            )
            if created_cond is not None:
                created_cond.last_transition_time = time.time()
            if self._write_status(job):
                self.recorder.event("TPUJob", key, "JobResumed")
            # fall through to ordinary admission: the eviction counter
            # makes the relaunched gang resume from checkpoint

        # Elastic world sizing: admit + render at the EFFECTIVE worker
        # count (status.elastic_replicas), not the spec-desired one — the
        # spec stays the user's intent, the status carries the resize.
        self._clamp_elastic_floor(job)
        self._apply_elastic_override(job)

        # Gang admission (SURVEY.md §7 hard part 1)
        ga = self.allocator.admit(job)
        if ga is None:
            ga = self._try_preempt(job)
        self._export_capacity_gauges()
        if ga is None:
            self.recorder.event(
                "TPUJob", key, "GangPending",
                f"insufficient capacity for {job.spec.tpu.accelerator} "
                f"x{job.spec.tpu.num_slices}",
            )
            self.metrics.inc("tpujob.gang_pending_total")
            timeout = job.spec.run_policy.scheduling.admission_timeout_s
            created = helpers.get_condition(job.status, JobConditionType.CREATED)
            # The timeout bounds INITIAL admission only (never-started
            # jobs). A job that already ran can land here after a demand
            # edit the pool can't satisfy (allocator kept the old gang)
            # or after being PREEMPTED (gang released, awaiting
            # re-admission) — measuring either against job-creation time
            # would insta-fail a long-running job.
            if (
                helpers.has_condition(job.status, JobConditionType.RUNNING)
                or job.status.start_time is not None
                or job.status.preemptions > 0
            ):
                self.controller.enqueue_after(key, PENDING_REQUEUE_S)
                return
            if timeout and created and time.time() - created.last_transition_time > timeout:
                helpers.set_condition(
                    job.status, JobConditionType.FAILED,
                    reason="AdmissionTimeout",
                    message=f"gang not admitted within {timeout}s",
                )
                self._write_status(job)
                return
            if changed:
                self._write_status(job)
            self.controller.enqueue_after(key, PENDING_REQUEUE_S)
            return

        # Deadline enforcement
        rp = job.spec.run_policy
        if (
            rp.active_deadline_seconds
            and job.status.start_time
            and time.time() - job.status.start_time > rp.active_deadline_seconds
        ):
            helpers.set_condition(
                job.status, JobConditionType.FAILED,
                reason="DeadlineExceeded",
                message=f"active for more than {rp.active_deadline_seconds}s",
            )
            self.recorder.event("TPUJob", key, "DeadlineExceeded")
            self._delete_job_pods(job, only_phases=None)
            self._write_status(job)
            return

        self._reconcile_replicas(job, ga, status_changed=changed)

    # ------------------------------------------------------- replica logic

    def _observed_pods(self, job: TPUJob) -> List[Pod]:
        return self.pods.list(job.metadata.namespace, L.job_selector(job.metadata.name))

    def _suspend(self, job: TPUJob) -> None:
        """Evict a suspended job's gang (idempotent: re-syncs of an
        already-suspended job are no-ops). The eviction bumps the same
        counter preemption uses, so un-suspending resumes from
        checkpoint without touching backoff_limit."""
        key = job.metadata.key
        if helpers.has_condition(job.status, JobConditionType.SUSPENDED):
            # already parked; make sure stragglers are gone AND the gang
            # is released (level-triggered: a transient failure between
            # the first pass's status write and its release must not
            # leak the slices for the park's duration — release is
            # idempotent)
            for pod in self._observed_pods(job):
                if pod.metadata.deletion_timestamp is None:
                    self._delete_pod(job.metadata.namespace, pod.metadata.name)
            self.allocator.release(job.metadata.uid)
            self._export_capacity_gauges()
            return
        live = [
            p for p in self._observed_pods(job)
            if p.metadata.deletion_timestamp is None
        ]
        had_gang = self.allocator.assignment(job.metadata.uid) is not None
        # pods already draining from a prior eviction are NOT a live
        # incarnation — counting them would inflate the resume lineage
        if had_gang or live:
            job.status.preemptions += 1
            self._preemptions_floor[key] = job.status.preemptions
        helpers.set_condition(
            job.status, JobConditionType.SUSPENDED,
            reason="JobSuspended",
            message=f"suspension {job.status.preemptions} (RunPolicy.suspend)",
        )
        # pause the active-deadline clock (kueue semantics: suspend
        # resets startTime) — parked hours must not count against
        # active_deadline_seconds; re-admission restamps it
        job.status.start_time = None
        if not self._write_status(job):
            return  # conflict: re-enqueued sync redoes the accounting
        self.recorder.event("TPUJob", key, "JobSuspended")
        self.metrics.inc("tpujob.suspensions_total")
        self._delete_job_pods(job, only_phases=None)
        self.allocator.release(job.metadata.uid)
        self._export_capacity_gauges()

    def _try_preempt(self, job: TPUJob):
        """Priority preemption: when admission fails, evict the cheapest
        set of strictly-lower-priority same-generation gangs whose
        release provably lets this job admit (allocator dry-run — no
        feasible plan means NOBODY is evicted: evicting without one
        would livelock the cluster, churning victims while the job still
        never fits). The release-and-admit is ONE atomic allocator
        operation — a victim's own concurrent sync must not re-admit
        itself into the freed capacity ahead of the preemptor (priority
        inversion that would force a second eviction). Each victim's
        ``preemptions`` counter bumps so its eventual re-admission
        resumes from checkpoint without consuming backoff_limit; victim
        pods drain after the swap (k8s-style grace overlap). Returns the
        preemptor's GangAssignment, or None."""
        my_pri = job.spec.run_policy.scheduling.priority
        if my_pri <= 0 or not job.spec.run_policy.scheduling.gang:
            return None
        from tfk8s_tpu.utils import topology as topo

        try:
            my_gen = topo.parse_accelerator(
                job.spec.tpu.accelerator, job.spec.tpu.topology
            ).generation
        except topo.TopologyError:
            return None
        if my_gen == "cpu":
            return None  # hermetic capacity is unlimited; nothing to evict

        def victim_key(v: TPUJob):
            # lowest priority first; among equals, youngest first (it has
            # the least sunk work)
            return (
                v.spec.run_policy.scheduling.priority,
                -(v.metadata.creation_timestamp or 0),
            )

        candidates = []
        for v in self.jobs.list(None):
            if v.metadata.uid == job.metadata.uid:
                continue
            if helpers.is_finished(v.status):
                continue
            if v.spec.run_policy.scheduling.priority >= my_pri:
                continue
            if self.allocator.assignment(v.metadata.uid) is None:
                continue
            try:
                v_gen = topo.parse_accelerator(
                    v.spec.tpu.accelerator, v.spec.tpu.topology
                ).generation
            except topo.TopologyError:
                continue
            if v_gen != my_gen:
                continue
            candidates.append(v)
        if not candidates:
            return None
        ordered = sorted(candidates, key=victim_key)
        plan = self.allocator.preemption_plan(
            job, [v.metadata.uid for v in ordered]
        )
        if plan is None:
            return None
        victims = [v for v in ordered if v.metadata.uid in set(plan)]
        # 1) atomic swap FIRST: victims' boxes -> preemptor's gang. On
        #    failure (a victim finished/released between plan and swap,
        #    shrinking the freed capacity) NOTHING happened — no status
        #    writes to roll back, no pods deleted for no benefit.
        ga = self.allocator.admit_with_preemption(
            job, [v.metadata.uid for v in victims]
        )
        if ga is None:
            return None
        # 2) persist each victim's eviction (checkpoint-resume contract)
        #    and drain its pods; its next sync re-queues it for capacity
        for victim in victims:
            if not self._persist_preemption(job, victim, my_pri):
                # narrow double-fault window (finished in the race, or
                # persistent write conflict): the eviction stands — log
                # so the missing resume counter is diagnosable
                log.warning(
                    "preempted %s but could not persist its eviction "
                    "counter", victim.metadata.key,
                )
            self._delete_job_pods(victim, only_phases=None)
            self.controller.enqueue_key(victim.metadata.key)
            log.info(
                "preempted %s (priority %d) for %s (priority %d)",
                victim.metadata.key,
                victim.spec.run_policy.scheduling.priority,
                job.metadata.key, my_pri,
            )
        return ga

    def _persist_preemption(self, job: TPUJob, victim: TPUJob, my_pri: int) -> bool:
        """Persist one victim's preemption (status counter + condition +
        events). Runs AFTER the atomic swap, so it does not check the
        allocator; but the status write still re-validates the FRESH
        object — a victim that finished in the race window must not be
        resurrected: set_condition(RESTARTING) would clear its terminal
        condition and re-run a completed job."""
        vkey = victim.metadata.key
        for _ in range(3):
            try:
                fresh = self.cs.tpujobs(victim.metadata.namespace).get(
                    victim.metadata.name
                )
            except NotFound:
                return False
            if (
                helpers.is_finished(fresh.status)
                or fresh.metadata.uid != victim.metadata.uid
                or fresh.spec.run_policy.scheduling.priority >= my_pri
            ):
                return False
            fresh.status.preemptions += 1
            helpers.set_condition(
                fresh.status, JobConditionType.RESTARTING,
                reason="Preempted",
                message=(
                    f"preemption {fresh.status.preemptions} by higher-"
                    f"priority job {job.metadata.key} "
                    f"(priority {my_pri} > "
                    f"{fresh.spec.run_policy.scheduling.priority})"
                ),
            )
            try:
                self.cs.tpujobs(victim.metadata.namespace).update_status(fresh)
                break
            except Conflict:
                continue
            except NotFound:
                return False
        else:
            return False
        self._preemptions_floor[vkey] = fresh.status.preemptions
        self.recorder.event(
            "TPUJob", vkey, "Preempted",
            f"by {job.metadata.key} (priority {my_pri})",
        )
        self.recorder.event(
            "TPUJob", job.metadata.key, "PreemptedOther", vkey,
        )
        self.metrics.inc("tpujob.preemptions_total")
        return True

    def _check_node_liveness(self, job: TPUJob, observed) -> None:
        """Mark RUNNING pods on heartbeat-dead nodes Failed(NodeLost) —
        k8s node-lease semantics (module constants above). A dead node
        emits no pod events, so jobs with running pods are re-enqueued on
        a short period to keep this check live."""
        from tfk8s_tpu.runtime.kubelet import NODE_LEASE_PREFIX

        key = job.metadata.key
        ns = job.metadata.namespace
        now = time.time()
        running = [
            p for p in observed.values()
            if p.status.phase == PodPhase.RUNNING
            and p.metadata.deletion_timestamp is None
            and p.status.host
        ]
        # one Lease fetch per distinct HOST, not per pod — a gang's pods
        # share few hosts and this path re-runs every CHECK_PERIOD
        leases = self.cs.generic("Lease", "default")
        stale_by_host: dict = {}
        for host in {p.status.host for p in running}:
            try:
                lease = leases.get(NODE_LEASE_PREFIX + host)
            except NotFound:
                continue  # node never heartbeated; no liveness contract
            rt = lease.spec.renew_time
            if rt is None:
                rt = lease.spec.acquire_time or 0.0
            if now > rt + lease.spec.lease_duration_s * NODE_LOST_GRACE:
                stale_by_host[host] = (now - rt, lease.spec.lease_duration_s)
        for pod in running:
            if pod.status.host not in stale_by_host:
                continue
            age, duration = stale_by_host[pod.status.host]
            msg = (
                f"NodeLost: node {pod.status.host} lease stale for "
                f"{age:.1f}s (duration {duration}s)"
            )
            self.recorder.event("TPUJob", key, "NodeLost",
                                f"{pod.metadata.name}: {msg}")
            self.metrics.inc("tpujob.node_lost_pods_total")
            try:
                cur = self.cs.pods(ns).get(pod.metadata.name)
                if (
                    cur.metadata.uid != pod.metadata.uid
                    or cur.status.phase != PodPhase.RUNNING
                ):
                    continue
                # narrow status patch with an rv PRECONDITION: a pod that
                # reaches a terminal phase between the get and this write
                # must not be clobbered to NodeLost — the precondition
                # turns that race into a skipped write (the periodic node
                # check re-evaluates)
                self.cs.pods(ns).patch_status(
                    pod.metadata.name,
                    {"metadata": {
                        "resourceVersion": str(cur.metadata.resource_version)
                    },
                     "status": {
                        "phase": PodPhase.FAILED.value,
                        "message": msg,
                        "exitCode": None,
                    }},
                )
            except (Conflict, NotFound):
                continue
        if running:
            self.controller.enqueue_after(key, NODE_CHECK_PERIOD_S)

    def _reconcile_replicas(self, job: TPUJob, ga, status_changed: bool) -> None:
        ns, key = job.metadata.namespace, job.metadata.key
        # Never render from a stale restart count (informer cache may lag
        # the increment write by a sync or two) — the recreated gang's
        # TFK8S_GANG_RESTARTS / resume contract depends on it.
        floor = self._gang_restarts_floor.get(key, 0)
        if job.status.gang_restarts < floor:
            job.status.gang_restarts = floor
        pfloor = self._preemptions_floor.get(key, 0)
        if job.status.preemptions < pfloor:
            job.status.preemptions = pfloor
        with self.tracer.start_span("diff", attributes={"job": key}):
            desired_pods, desired_svcs = R.render_all(job, ga)
            desired_names = {p.metadata.name for p in desired_pods}
            desired_svc_names = {s.metadata.name for s in desired_svcs}
            observed = {p.metadata.name: p for p in self._observed_pods(job)}
            observed_svcs = {
                s.metadata.name
                for s in self.services.list(ns, L.job_selector(job.metadata.name))
            }
        self._check_node_liveness(job, observed)

        # Elastic / reclaim handling runs BEFORE the orphan and
        # stale-render deletions: a resize manages its own graceful drain
        # of old-world pods, which the hard-delete paths below would
        # preempt.
        if self._handle_elastic(job, observed):
            return
        if self._handle_drained(job, observed, desired_names):
            return

        # Orphans (scale-down or stale template): delete pods AND services.
        for pname, pod in observed.items():
            if pname not in desired_names and pod.metadata.deletion_timestamp is None:
                self._delete_pod(ns, pname)
        for sname in observed_svcs - desired_svc_names:
            try:
                self.cs.services(ns).delete(sname)
            except NotFound:
                pass

        # Stale renders (scale-up / template edit): a live pod whose
        # desired env differs from what it was started with cannot serve
        # the new cluster spec — the coordination contract
        # (TFK8S_NUM_PROCESSES / TFK8S_CLUSTER_SPEC / TFK8S_MESH,
        # trainer/replicas.py) is baked in at process start. Delete it;
        # level-triggered recreation (next sync) brings the gang back
        # consistent. Scaling a replica set therefore replaces the whole
        # gang in one reconcile pass — the honest TPU semantics (the
        # reference's async-PS world could add workers live; a
        # collective gang cannot, SURVEY.md §2 'Elastic/gang').
        # Allocator-derived placement keys are EXCLUDED from the diff:
        # the SliceAllocator is in-memory, so an operator restart
        # re-admits every job onto freshly-named boxes — a placement-key
        # diff would then spuriously gang-restart the whole cluster.
        desired_by_name = {p.metadata.name: p for p in desired_pods}
        for pname, pod in observed.items():
            want = desired_by_name.get(pname)
            if (
                want is not None
                and pod.metadata.deletion_timestamp is None
                and pod.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)
                and _contract_env(pod) != _contract_env(want)
            ):
                self.recorder.event(
                    "TPUJob", key, "PodReplaced",
                    f"{pname}: coordination env changed (scale or template edit)",
                )
                self._delete_pod(ns, pname)

        # Failure accounting before creation, so a gang restart deletes
        # pods instead of racing recreation.
        failed = [
            p for p in observed.values()
            if p.status.phase == PodPhase.FAILED and p.metadata.name in desired_names
        ]
        if failed and self._handle_failures(job, failed, observed):
            return  # terminal or gang-restarting; next events continue

        svcs_to_create = [
            svc for svc in desired_svcs if svc.metadata.name not in observed_svcs
        ]
        if svcs_to_create:
            self.cs.services(ns).create_many(svcs_to_create)
        # Gang pods are created through ONE batched rate-limiter acquire
        # (create_many): a whole gang pays a single token reservation
        # instead of one sleep per pod on the reconcile hot path.
        pods_to_create = []
        for pod in desired_pods:
            existing = observed.get(pod.metadata.name)
            if existing is None:
                # preserve restart lineage across in-place restarts
                restarts = self._pending_restart_counts.pop(pod.metadata.key, None)
                if restarts is not None:
                    pod.metadata.annotations[RESTARTS_ANNOTATION] = str(restarts)
                with self.tracer.start_span(
                    "pod.create", attributes={"pod": pod.metadata.key}
                ) as sp:
                    # the handoff across the control->data plane boundary:
                    # the kubelet (and through it the trainer) continues
                    # THIS span's trace — CRD update to step 1, one trace
                    if sp.traceparent and pod.spec.containers:
                        pod.spec.containers[0].env[TRACEPARENT_ENV] = (
                            sp.traceparent
                        )
                pods_to_create.append(pod)
        if pods_to_create:
            created = self.cs.pods(ns).create_many(pods_to_create)
            if created:
                self.metrics.inc(
                    "tpujob.pods_created_total", float(len(created))
                )

        self._update_job_status(job, status_changed)

    @staticmethod
    def _gang_restart_message(restart_no: int, failed_ids: List[str]) -> str:
        return f"restart {restart_no} after {failed_ids} failed"

    def _handle_failures(self, job: TPUJob, failed: List[Pod], observed) -> bool:
        """Returns True when reconcile should stop (terminal / restarting)."""
        key = job.metadata.key
        ns = job.metadata.namespace
        gang_mode = job.spec.run_policy.scheduling.gang

        def _is_evaluator(pod: Pod) -> bool:
            return (
                pod.metadata.labels.get(L.REPLICA_TYPE)
                == ReplicaType.EVALUATOR.value
            )

        # Replica-level policy: Never means a failure is permanent.
        # Evaluator failures are never JOB-fatal (success keys off the
        # compute replicas) — a Never-policy evaluator is left Failed
        # for inspection and dropped from further handling.
        for pod in failed:
            if pod.spec.restart_policy == RestartPolicy.NEVER:
                if _is_evaluator(pod):
                    self._record_evaluator_failure(key, pod)
                    continue
                helpers.set_condition(
                    job.status, JobConditionType.FAILED,
                    reason="PodFailed",
                    message=f"pod {pod.metadata.name} failed: {pod.status.message}",
                )
                self.recorder.event("TPUJob", key, "PodFailed", pod.metadata.name)
                self._write_status(job)
                return True
        failed = [
            p for p in failed
            if not (p.spec.restart_policy == RestartPolicy.NEVER and _is_evaluator(p))
        ]

        # Evaluator pods sit OUTSIDE the compute gang: they hold no slice
        # chips, so an evaluator crash is not slice loss — restart it in
        # place instead of burning a gang restart of healthy training
        # replicas (a wedged evaluator would otherwise cycle the whole job
        # to BackoffLimitExceeded).
        gang_failed = [p for p in failed if not _is_evaluator(p)]

        if gang_mode and gang_failed:
            failed = gang_failed  # evaluators don't drive gang accounting
            # Idempotent accounting FIRST — before the limit check: if a
            # sync re-observes failed pods whose episode was already
            # counted (a crash or stale cache between the status write
            # and pod deletion), it must neither burn a second unit of
            # backoff_limit NOR terminate the job — a stale observation
            # arriving after the final counted restart would otherwise
            # fire BackoffLimitExceeded before the last incarnation ever
            # ran (and, its pods already deleted, leave nothing behind).
            # Keyed by pod UID (not name): recreated pods reuse names but
            # get fresh UIDs, so a genuine repeat failure is a new
            # episode and still counts.
            failed_ids = sorted(
                f"{p.metadata.name}:{p.metadata.uid[:8]}" for p in failed
            )
            existing = helpers.get_condition(
                job.status, JobConditionType.RESTARTING
            )
            # Deliberately ignore existing.status: a stale Failed-pod
            # event can arrive AFTER the restarted gang went Running
            # (which flips RESTARTING to False) — the failed set's UIDs,
            # baked into the message, are the episode's real identity.
            already_counted = (
                existing is not None
                and existing.message
                == self._gang_restart_message(job.status.gang_restarts, failed_ids)
            )
            if already_counted:
                self._delete_job_pods(job, only_phases=None)
                return True
            # Slice loss is gang loss: restart everything from checkpoint
            # (SURVEY.md §2 'Elastic / gang semantics').
            limit = job.spec.run_policy.backoff_limit or 0
            if job.status.gang_restarts >= limit:
                helpers.set_condition(
                    job.status, JobConditionType.FAILED,
                    reason="BackoffLimitExceeded",
                    message=f"gang restarted {job.status.gang_restarts}x; limit {limit}",
                )
                self.recorder.event("TPUJob", key, "BackoffLimitExceeded")
                self._write_status(job)
                return True
            job.status.gang_restarts += 1
            helpers.set_condition(
                job.status, JobConditionType.RESTARTING,
                reason="GangRestart",
                message=self._gang_restart_message(
                    job.status.gang_restarts, failed_ids
                ),
            )
            # Persist the restart count BEFORE deleting pods: if this
            # write conflicts, stop here — the failed pods are still
            # observable, so the re-enqueued sync redoes the accounting.
            # Deleting first would lose the increment on conflict
            # (restart without trace).
            if not self._write_status(job):
                return True
            # Floor for stale-cache syncs: the recreate pass must
            # never render pods with a pre-increment restart count.
            self._gang_restarts_floor[key] = job.status.gang_restarts
            self.recorder.event(
                "TPUJob", key, "GangRestart", f"#{job.status.gang_restarts}"
            )
            self.metrics.inc("tpujob.gang_restarts_total")
            self._delete_job_pods(job, only_phases=None)
            return True

        # Per-pod in-place restart (OnFailure/Always/ExitCode)
        for pod in failed:
            restarts = int(pod.metadata.annotations.get(RESTARTS_ANNOTATION, "0"))
            rspec = None
            rt = pod.metadata.labels.get(L.REPLICA_TYPE)
            if rt:
                rspec = job.spec.replica_specs.get(ReplicaType(rt))
            max_restarts = rspec.max_restarts if rspec else 0
            if restarts >= (max_restarts or 0):
                if _is_evaluator(pod):
                    # exhausted evaluator: left Failed, job unaffected
                    self._record_evaluator_failure(key, pod)
                    continue
                helpers.set_condition(
                    job.status, JobConditionType.FAILED,
                    reason="BackoffLimitExceeded",
                    message=f"pod {pod.metadata.name} failed {restarts + 1}x",
                )
                self._write_status(job)
                return True
            self._delete_pod(ns, pod.metadata.name)
            self.recorder.event(
                "TPUJob", key, "PodRestart",
                f"{pod.metadata.name} restart #{restarts + 1}",
            )
            # The recreated pod inherits the incremented restart count
            # (keyed by namespace/name so same-named jobs in different
            # namespaces can't cross-contaminate lineage).
            self._pending_restart_counts[pod.metadata.key] = restarts + 1
        return False


    # ------------------------------------------------------ elastic resize

    def _clamp_elastic_floor(self, job: TPUJob) -> None:
        """Never act on a world OLDER than one this controller already
        committed (informer cache may lag the resize's status write)."""
        floor = self._elastic_floor.get(job.metadata.key)
        if floor is not None and job.status.world_version < floor[0]:
            job.status.world_version, job.status.elastic_replicas = floor

    def _apply_elastic_override(self, job: TPUJob) -> None:
        """Rewrite the WORKING COPY's spec to the effective elastic size:
        Worker replicas from ``status.elastic_replicas``, and (non-cpu)
        num_slices/mesh re-derived so the gang-consistency invariant
        (one process per host, mesh product == chips) holds at the
        resized world. The stored spec keeps the user's desired count —
        stashed on the copy for the scale-up path."""
        w = job.spec.replica_specs.get(ReplicaType.WORKER)
        if w is None:
            return
        job._elastic_desired = w.replicas
        eff = job.status.elastic_replicas
        if (
            job.spec.run_policy.elastic is None
            or eff is None
            or eff == w.replicas
        ):
            return
        from tfk8s_tpu.utils import topology as topo

        w.replicas = eff
        try:
            info = topo.parse_accelerator(
                job.spec.tpu.accelerator, job.spec.tpu.topology
            )
        except topo.TopologyError:
            return
        if info.generation != "cpu" and info.hosts:
            job.spec.tpu.num_slices = max(eff // info.hosts, 1)
            if job.spec.mesh is not None and set(job.spec.mesh.axes) == {"data"}:
                # validation restricts elastic TPU jobs to a pure
                # data-parallel mesh exactly so this re-derivation is safe
                job.spec.mesh.axes["data"] = (
                    info.chips * job.spec.tpu.num_slices
                )

    @staticmethod
    def _pod_world(pod: Pod) -> int:
        try:
            return int(pod.spec.containers[0].env.get("TFK8S_WORLD_VERSION", "0"))
        except (ValueError, IndexError):
            return 0

    def _deliver_drain(self, ns: str, pod: Pod, deadline: float) -> None:
        """Stamp the reclaim-notice annotation on a pod (idempotent); the
        kubelet's watch turns it into the entrypoint's soft drain
        signal."""
        from tfk8s_tpu.runtime.kubelet import RECLAIM_AT_ANNOTATION, reclaim_patch

        if RECLAIM_AT_ANNOTATION in pod.metadata.annotations:
            return
        try:
            self.cs.pods(ns).patch(pod.metadata.name, reclaim_patch(deadline))
        except (Conflict, NotFound):
            pass

    def _handle_elastic(self, job: TPUJob, observed) -> bool:
        """Elastic world sizing (RunPolicy.elastic). Returns True when
        this sync is consumed by resize management:

        - a Worker drained (or sits under a reclaim notice) and the
          survivors still satisfy ``min_replicas`` -> begin a resize DOWN:
          bump the world version, drain the survivors so they checkpoint
          at their freshest step, and re-render at the surviving count —
          no backoff_limit burned;
        - a resize is in flight -> shepherd old-world pods out (drained/
          terminal ones deleted, stragglers hard-deleted past the grace
          deadline) before the new gang renders;
        - the job runs below its desired size and the debounce elapsed ->
          resize UP toward the spec count when capacity allows.
        """
        el = job.spec.run_policy.elastic
        if el is None or not job.spec.run_policy.scheduling.gang:
            return False
        from tfk8s_tpu.runtime.kubelet import (
            RECLAIM_AT_ANNOTATION,
            parse_reclaim_at,
        )
        from tfk8s_tpu.utils import topology as topo

        key, ns = job.metadata.key, job.metadata.namespace
        now = time.time()
        wv = job.status.world_version
        live = [
            p for p in observed.values()
            if p.metadata.deletion_timestamp is None
        ]

        # -- resize in flight: old-world pods still present ---------------
        if wv > 0:
            stale = [p for p in live if self._pod_world(p) != wv]
            if stale:
                for p in stale:
                    if p.status.phase in (
                        PodPhase.DRAINED, PodPhase.SUCCEEDED, PodPhase.FAILED
                    ):
                        self._delete_pod(ns, p.metadata.name)
                        continue
                    if RECLAIM_AT_ANNOTATION not in p.metadata.annotations:
                        self._deliver_drain(
                            ns, p, now + RESIZE_DRAIN_GRACE_S
                        )
                        continue
                    # a malformed stamp makes the grace unknowable: treat
                    # it as already expired rather than waiting forever
                    deadline = parse_reclaim_at(p)
                    if deadline is None:
                        deadline = now
                    if now >= deadline:
                        # grace exhausted: SIGKILL equivalent
                        self._delete_pod(ns, p.metadata.name)
                self.controller.enqueue_after(key, RESIZE_POLL_S)
                return True

        try:
            info = topo.parse_accelerator(
                job.spec.tpu.accelerator, job.spec.tpu.topology
            )
        except topo.TopologyError:
            return False

        workers = [
            p for p in live
            if p.metadata.labels.get(L.REPLICA_TYPE) == ReplicaType.WORKER.value
        ]
        victims = [
            p for p in workers
            if p.status.phase == PodPhase.DRAINED
            or RECLAIM_AT_ANNOTATION in p.metadata.annotations
        ]
        survivors = [
            p for p in workers
            if p not in victims
            and p.status.phase in (
                PodPhase.PENDING, PodPhase.SCHEDULED, PodPhase.RUNNING
            )
        ]
        if any(
            p.status.phase == PodPhase.FAILED
            and RECLAIM_AT_ANNOTATION not in p.metadata.annotations
            for p in workers
        ):
            # a COLD crash (no notice) in the same sync as a resize
            # trigger: defer the resize so the ordinary failure machinery
            # accounts it first (backoff, restart floor, events) — a
            # world-version bump here would reclassify the carcass as a
            # stale-world pod and the shepherd would delete it silently,
            # exempting crashes from backoff whenever they coincide with
            # a resize window
            return False

        # -- resize down: capacity left; shrink to the survivors ----------
        if victims:
            new_count = len(survivors)
            if info.generation != "cpu" and info.hosts:
                # slice granularity: a partially-populated slice cannot
                # run — floor to the slice boundary
                new_count = (new_count // info.hosts) * info.hosts
            if new_count >= max(el.min_replicas or 1, 1):
                self._begin_resize(
                    job, new_count, "down",
                    drain_pods=[p for p in live if p not in victims],
                    delete_pods=[
                        p for p in victims
                        if p.status.phase == PodPhase.DRAINED
                    ],
                )
                return True
            # below min_replicas: fall through — _handle_drained answers
            # with a preemption-style whole-gang restart (re-admission at
            # full size when capacity returns)
            return False

        # -- debounced scale back up toward the desired count -------------
        eff = job.status.elastic_replicas
        desired = getattr(job, "_elastic_desired", None)
        if eff is None or desired is None or eff >= desired:
            return False
        debounce = el.resize_debounce_s or 0.0
        remaining = debounce - (now - self._last_resize.get(key, 0.0))
        if remaining > 0:
            self.controller.enqueue_after(key, min(remaining + 0.05, debounce))
            return False  # keep running at the current size meanwhile
        target = min(desired, el.max_replicas or desired)
        if info.generation != "cpu" and info.hosts:
            extra_slices = -(-(target - eff) // info.hosts)  # ceil
            if self.allocator.free_slices(job.spec.tpu.accelerator) < extra_slices:
                self.controller.enqueue_after(key, PENDING_REQUEUE_S)
                return False  # capacity hasn't returned yet
        self._begin_resize(job, target, "up", drain_pods=live, delete_pods=[])
        return True

    def _begin_resize(
        self, job: TPUJob, new_count: int, direction: str,
        drain_pods: List[Pod], delete_pods: List[Pod],
    ) -> None:
        """Commit the resize decision: new world version + effective count
        in status FIRST (conflict -> the re-enqueued sync redoes the
        accounting off fresh state), then drain every pod of the old
        world so each commits a checkpoint at its freshest step before
        the gang re-forms."""
        key, ns = job.metadata.key, job.metadata.namespace
        desired = getattr(job, "_elastic_desired", None) or new_count
        job.status.elastic_replicas = None if new_count == desired else new_count
        job.status.world_version += 1
        wv = job.status.world_version
        helpers.set_condition(
            job.status, JobConditionType.RESTARTING,
            reason="Resizing",
            message=f"{direction} to {new_count} workers (world v{wv})",
        )
        if not self._write_status(job):
            return
        self._elastic_floor[key] = (wv, job.status.elastic_replicas)
        now = time.time()
        self._last_resize[key] = now
        self._resize_started[key] = (now, direction)
        self.recorder.event(
            "TPUJob", key, "ElasticResize",
            f"{direction} -> {new_count} workers (world v{wv})",
        )
        self.metrics.inc(
            "tfk8s_elastic_resizes_total", 1.0, {"direction": direction}
        )
        for p in delete_pods:
            self._delete_pod(ns, p.metadata.name)
        deadline = now + RESIZE_DRAIN_GRACE_S
        for p in drain_pods:
            self._deliver_drain(ns, p, deadline)
        self.controller.enqueue_after(key, RESIZE_POLL_S)

    def _handle_drained(self, job: TPUJob, observed, desired_names) -> bool:
        """Drained pods NOT consumed by an elastic resize. A drained
        compute pod on a fixed-size gang (or with survivors below
        min_replicas) is a whole-gang preemption-style restart: reclaim
        is not a failure, so ``backoff_limit`` is untouched and the
        relaunched gang resumes from the drain checkpoint. Drained
        evaluators / per-pod-mode pods are simply replaced."""
        key = job.metadata.key
        drained_gang: List[Pod] = []
        for p in observed.values():
            if (
                p.status.phase != PodPhase.DRAINED
                or p.metadata.deletion_timestamp is not None
            ):
                continue
            is_eval = (
                p.metadata.labels.get(L.REPLICA_TYPE)
                == ReplicaType.EVALUATOR.value
            )
            if (
                p.metadata.name not in desired_names
                or is_eval
                or not job.spec.run_policy.scheduling.gang
            ):
                # outside the gang contract: replace in place, no
                # accounting (a fresh pod re-runs from checkpoint or
                # from its own poll loop)
                self._delete_pod(job.metadata.namespace, p.metadata.name)
                continue
            drained_gang.append(p)
        if not drained_gang:
            return False
        ids = sorted(
            f"{p.metadata.name}:{p.metadata.uid[:8]}" for p in drained_gang
        )
        existing = helpers.get_condition(
            job.status, JobConditionType.RESTARTING
        )
        already = (
            existing is not None
            and existing.message
            == self._reclaim_restart_message(job.status.preemptions, ids)
        )
        if already:
            self._delete_job_pods(job, only_phases=None)
            return True
        job.status.preemptions += 1
        helpers.set_condition(
            job.status, JobConditionType.RESTARTING,
            reason="Reclaimed",
            message=self._reclaim_restart_message(job.status.preemptions, ids),
        )
        if not self._write_status(job):
            return True
        self._preemptions_floor[key] = job.status.preemptions
        self.recorder.event(
            "TPUJob", key, "ReclaimRestart",
            f"#{job.status.preemptions} after {ids} drained",
        )
        self.metrics.inc("tpujob.preemptions_total")
        self._delete_job_pods(job, only_phases=None)
        return True

    @staticmethod
    def _reclaim_restart_message(n: int, ids: List[str]) -> str:
        return f"reclaim restart {n} after {ids} drained"

    def _export_capacity_gauges(self) -> None:
        """Free whole-slice inventory per accelerator type, as gauges.
        Cheap when nothing changed: the allocator's version counter
        gates the O(types x boxes) recomputation off the hot reconcile
        path (admit is called on every sync and is usually a no-op)."""
        v = self.allocator.version
        if v == getattr(self, "_gauges_version", None):
            return
        self._gauges_version = v
        for acc, n in self.allocator.capacity_summary().items():
            self.metrics.set_gauge(
                "gang.free_slices", float(n), {"accelerator": acc}
            )

    def _record_evaluator_failure(self, key: str, pod: Pod) -> None:
        """Once-per-pod-uid event: the terminally-Failed evaluator pod is
        kept around, so every subsequent reconcile re-observes it — without
        dedup the event log floods. Keyed by job so deletion can prune."""
        entry = (key, pod.metadata.uid)
        if entry in self._evaluator_failures_seen:
            return
        self._evaluator_failures_seen.add(entry)
        self.recorder.event("TPUJob", key, "EvaluatorFailed", pod.metadata.name)

    def _prune_job_state(self, key: str) -> None:
        """Drop ALL controller-side scratch for a deleted job (evaluator
        failure dedup, restart/preemption/elastic floors, resize clocks,
        pending per-pod restart lineage) — a future job reusing the name
        must not inherit a stale floor (it would render
        TFK8S_GANG_RESTARTS > 0 and try to resume a checkpoint that
        isn't its own), and a long-lived operator must not leak one map
        entry per job it ever saw."""
        self._evaluator_failures_seen = {
            e for e in self._evaluator_failures_seen if e[0] != key
        }
        self._gang_restarts_floor.pop(key, None)
        self._preemptions_floor.pop(key, None)
        self._elastic_floor.pop(key, None)
        self._resize_started.pop(key, None)
        self._last_resize.pop(key, None)
        # _pending_restart_counts is keyed by POD key; a pod belongs to
        # this job iff it matches <ns>/<job>-<replica-type>-<index> (exact
        # pattern, not a prefix — job "a" must not prune pods of job
        # "a-worker", whose names continue past the digits)
        ns, name = key.split("/", 1)
        types = "|".join(rt.value.lower() for rt in ReplicaType)
        pat = re.compile(
            rf"^{re.escape(ns)}/{re.escape(name)}-(?:{types})-\d+$"
        )
        for pkey in [k for k in self._pending_restart_counts if pat.match(k)]:
            self._pending_restart_counts.pop(pkey, None)

    def _delete_pod(self, ns: str, name: str) -> None:
        try:
            self.cs.pods(ns).delete(name)
            self.metrics.inc("tpujob.pods_deleted_total")
        except NotFound:
            pass

    def _delete_job_pods(self, job: TPUJob, only_phases) -> None:
        for p in self._observed_pods(job):
            if only_phases is None or p.status.phase in only_phases:
                self._delete_pod(job.metadata.namespace, p.metadata.name)

    # ----------------------------------------------------------- status

    def _update_job_status(self, job: TPUJob, already_changed: bool) -> None:
        key = job.metadata.key
        observed = self._observed_pods(job)
        changed = already_changed

        new_statuses = {}
        for rt in helpers.sorted_replica_types(job):
            rs = ReplicaStatus()
            for p in observed:
                if p.metadata.labels.get(L.REPLICA_TYPE) != rt.value:
                    continue
                if p.status.phase in (PodPhase.PENDING, PodPhase.SCHEDULED, PodPhase.RUNNING):
                    rs.active += 1
                elif p.status.phase == PodPhase.SUCCEEDED:
                    rs.succeeded += 1
                elif p.status.phase == PodPhase.FAILED:
                    rs.failed += 1
                rs.restarts += int(p.metadata.annotations.get(RESTARTS_ANNOTATION, "0"))
            new_statuses[rt] = rs
        if new_statuses != job.status.replica_statuses:
            job.status.replica_statuses = new_statuses
            changed = True

        # Success: every compute replica ran to completion (chief acts as
        # the completion oracle when present).
        compute_types = [
            rt for rt in (ReplicaType.CHIEF, ReplicaType.WORKER)
            if rt in job.spec.replica_specs
        ]
        def _count(rt):
            return job.spec.replica_specs[rt].replicas or 0

        if ReplicaType.CHIEF in compute_types:
            done = new_statuses[ReplicaType.CHIEF].succeeded >= _count(ReplicaType.CHIEF)
        else:
            done = all(new_statuses[rt].succeeded >= _count(rt) for rt in compute_types)

        n_active = sum(rs.active for rs in new_statuses.values())
        n_expected = helpers.total_replicas(job)
        # Permanently-failed evaluators (left in place by design — see
        # _handle_failures) must not block the Running transition or the
        # start_time stamp active_deadline_seconds hangs off.
        n_dead_evaluators = sum(
            1 for p in observed
            if p.status.phase == PodPhase.FAILED
            and p.metadata.labels.get(L.REPLICA_TYPE) == ReplicaType.EVALUATOR.value
        )

        if done:
            if helpers.set_condition(
                job.status, JobConditionType.SUCCEEDED, reason="JobSucceeded"
            ):
                job.status.completion_time = time.time()
                self.recorder.event("TPUJob", key, "JobSucceeded")
                self.metrics.inc("tpujob.succeeded_total")
                changed = True
            self.allocator.release(job.metadata.uid)
            self._export_capacity_gauges()
        elif n_active == n_expected - n_dead_evaluators and n_active > 0:
            running = all(
                p.status.phase == PodPhase.RUNNING for p in observed
                if p.metadata.labels.get(L.REPLICA_TYPE)
                and not (
                    p.status.phase == PodPhase.FAILED
                    and p.metadata.labels.get(L.REPLICA_TYPE)
                    == ReplicaType.EVALUATOR.value
                )
            )
            if running:
                if job.status.start_time is None:
                    job.status.start_time = time.time()
                    changed = True
                if helpers.set_condition(
                    job.status, JobConditionType.RUNNING, reason="AllReplicasRunning"
                ):
                    self.recorder.event("TPUJob", key, "JobRunning")
                    changed = True
                started = self._resize_started.pop(key, None)
                if started is not None:
                    # the resized gang is fully Running: close the resize
                    # into the per-job recovery gauge + one trace span
                    t0, direction = started
                    end = time.time()
                    self.metrics.set_gauge(
                        "tpujob.recovery_seconds", end - t0,
                        {"namespace": job.metadata.namespace,
                         "job": job.metadata.name},
                    )
                    self.tracer.record_span(
                        "job.resize", start=t0, end=end,
                        attributes={"job": key, "direction": direction},
                    )
                    self.recorder.event(
                        "TPUJob", key, "ResizeComplete",
                        f"{direction} recovered in {end - t0:.2f}s",
                    )

        if changed:
            self._write_status(job)

    def _write_status(self, job: TPUJob) -> bool:
        """Returns True when the write landed; False on deletion. Rides the
        PATCH /status subresource: the controller is the sole owner of job
        status, so a merge-patch of the full status needs no
        resourceVersion and can never 409 against concurrent spec writers
        (scale/suspend/apply) — the happy path is conflict-free.

        Deep-compares the computed status against the cached server state
        FIRST (the ``_status_baseline`` stamped by sync): an unchanged
        status skips the round trip entirely — the controller being the
        sole status owner makes the cached value an honest baseline, and
        the level-triggered resync covers the stale-cache corner. Skips
        are counted (``tfk8s_status_patches_skipped_total``)."""
        from tfk8s_tpu.api import serde

        wire_status = serde.to_wire(job.status)
        baseline = getattr(job, "_status_baseline", None)
        if baseline is not None and wire_status == baseline:
            self.metrics.inc("tfk8s_status_patches_skipped_total")
            return True
        # merge-patch can't delete map keys it doesn't mention: a replica
        # type REMOVED from the spec must carry an explicit null or its
        # stale replicaStatuses entry survives server-side and every
        # reconcile re-detects a diff — an endless status-write loop. The
        # type set is the finite enum, so the nulls are bounded. Padding
        # goes on a copy: wire_status doubles as the next baseline and
        # must stay comparable to a future to_wire().
        payload = dict(wire_status)
        rs = payload.get("replicaStatuses")
        if isinstance(rs, dict):
            rs = dict(rs)
            for rt in ReplicaType:
                rs.setdefault(rt.value, None)
            payload["replicaStatuses"] = rs
        with self.tracer.start_span(
            "status.update", attributes={"job": job.metadata.key}
        ):
            try:
                self.cs.tpujobs(job.metadata.namespace).patch_status(
                    job.metadata.name, {"status": payload}
                )
                job._status_baseline = wire_status
                return True
            except NotFound:
                return False

    # ------------------------------------------------------ teardown paths

    def _cleanup_finished(self, job: TPUJob) -> None:
        """Clean-pod policy + TTL for finished jobs; slices are returned to
        the pool either way."""
        self.allocator.release(job.metadata.uid)
        self._export_capacity_gauges()
        policy = job.spec.run_policy.clean_pod_policy or CleanPodPolicy.RUNNING
        if policy == CleanPodPolicy.ALL:
            self._delete_job_pods(job, only_phases=None)
            self._delete_job_services(job)
        elif policy == CleanPodPolicy.RUNNING:
            self._delete_job_pods(
                job, only_phases=(PodPhase.PENDING, PodPhase.SCHEDULED, PodPhase.RUNNING)
            )
        ttl = job.spec.run_policy.ttl_seconds_after_finished
        if ttl is not None and job.status.completion_time:
            age = time.time() - job.status.completion_time
            if age >= ttl:
                try:
                    self.cs.tpujobs(job.metadata.namespace).delete(job.metadata.name)
                except NotFound:
                    pass
            else:
                self.controller.enqueue_after(job.metadata.key, ttl - age + 0.05)

    def _delete_job_services(self, job: TPUJob) -> None:
        for s in self.services.list(
            job.metadata.namespace, L.job_selector(job.metadata.name)
        ):
            try:
                self.cs.services(job.metadata.namespace).delete(s.metadata.name)
            except NotFound:
                pass

    def _delete_job_events(self, job: TPUJob) -> None:
        """Garbage-collect the job's mirrored Event objects (k8s expires
        events by TTL; here deletion rides job teardown)."""
        ns, key = job.metadata.namespace, job.metadata.key
        try:
            client = self.cs.generic("Event", ns)
            events, _rv = client.list()
            for ev in events:
                if getattr(ev, "involved_key", "") == key:
                    try:
                        client.delete(ev.metadata.name)
                    except NotFound:
                        pass
        except Exception as e:  # noqa: BLE001 — event GC is best-effort
            log.debug("event GC for %s failed: %s", key, e)

    def _finalize(self, job: TPUJob) -> None:
        """Deletion path (SURVEY.md §3.4): tear everything down, then strip
        the finalizer so the store completes the delete."""
        key = job.metadata.key
        self._delete_job_pods(job, only_phases=None)
        self._delete_job_services(job)
        self.allocator.release(job.metadata.uid)
        self._export_capacity_gauges()
        self._prune_job_state(key)
        if FINALIZER in job.metadata.finalizers:
            remaining = [f for f in job.metadata.finalizers if f != FINALIZER]
            try:
                # stripping the finalizer via PATCH completes the delete
                # server-side when ours was the last. rv PRECONDITION: the
                # list replaces wholesale, and completing the delete off a
                # stale list could drop a foreign finalizer added since —
                # destroying its owner's chance to ever run cleanup.
                self.cs.tpujobs(job.metadata.namespace).patch(
                    job.metadata.name,
                    {"metadata": {
                        "resourceVersion": str(job.metadata.resource_version),
                        "finalizers": remaining,
                    }},
                )
            except Conflict:
                # deletion NOT complete yet — retry off the fresh object
                # without wiping event history or recording JobDeleted
                self.controller.enqueue_key(key)
                return
            except NotFound:
                return
        self.recorder.event("TPUJob", key, "JobDeleted")
        # AFTER the terminal event, so its mirrored object is GC'd too —
        # a deleted job leaves no Event objects behind
        self.recorder.flush()
        self._delete_job_events(job)
        # ... and no /metrics series either (same leave-nothing contract):
        # label-based GC removes exactly this job's labeled series
        self.metrics.remove_labels(
            {"namespace": job.metadata.namespace, "job": job.metadata.name}
        )
