"""Selector labels tying pods/services to their job, replica set, and task
index — the ``pkg/trainer/labels.go`` equivalent (SURVEY.md C19).
"""

from __future__ import annotations

from typing import Dict

from tfk8s_tpu.api.types import ReplicaType

JOB_NAME = "tfk8s.dev/job-name"
REPLICA_TYPE = "tfk8s.dev/replica-type"
REPLICA_INDEX = "tfk8s.dev/replica-index"
SLICE_ID = "tfk8s.dev/slice-id"
HOST_INDEX = "tfk8s.dev/host-index"
CONTROLLER = "tfk8s.dev/controller"
CONTROLLER_NAME = "tpujob-operator"
# Serving (TPUServe) pods: owner + the pod-template hash they were
# rendered from (the rolling-update version identity, Deployment's
# pod-template-hash analogue).
SERVE_NAME = "tfk8s.dev/serve-name"
SERVE_VERSION = "tfk8s.dev/serve-version"
# Disaggregated serving: which phase pool a replica belongs to
# ("prefill" / "decode"; absent on single-pool serves)
SERVE_PHASE = "tfk8s.dev/serve-phase"


def job_selector(job_name: str) -> Dict[str, str]:
    """Selector matching every pod/service of a job."""
    return {JOB_NAME: job_name, CONTROLLER: CONTROLLER_NAME}


def replica_labels(job_name: str, rtype: ReplicaType, index: int) -> Dict[str, str]:
    return {
        JOB_NAME: job_name,
        CONTROLLER: CONTROLLER_NAME,
        REPLICA_TYPE: rtype.value,
        REPLICA_INDEX: str(index),
    }


def replica_type_selector(job_name: str, rtype: ReplicaType) -> Dict[str, str]:
    return {**job_selector(job_name), REPLICA_TYPE: rtype.value}


def serve_selector(serve_name: str) -> Dict[str, str]:
    """Selector matching every serving replica pod of a TPUServe."""
    return {SERVE_NAME: serve_name, CONTROLLER: CONTROLLER_NAME}


def serve_version_labels(serve_name: str, version: str) -> Dict[str, str]:
    return {**serve_selector(serve_name), SERVE_VERSION: version}


def serve_phase_selector(serve_name: str, phase: str) -> Dict[str, str]:
    """Selector matching ONE phase pool of a disaggregated serve."""
    return {**serve_selector(serve_name), SERVE_PHASE: phase}
