"""Gang scheduling: all-or-nothing admission of a job's replicas onto
ICI-contiguous TPU slices.

The reference has no equivalent — k8s Jobs admit pods independently
(k8s-operator.md:44-49) and a partially-scheduled TF cluster just wedges.
On TPU the hardware forces the issue: a slice exists or it doesn't, and a
job's mesh spans whole slices. This module is the SURVEY.md §7 hard-part-1
answer: a slice inventory + atomic admission, so the controller either gets
every host of every slice it needs or nothing, and slice loss releases the
whole gang.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional

from tfk8s_tpu.api.types import TPUJob
from tfk8s_tpu.utils import topology as topo
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("gang")


@dataclasses.dataclass(frozen=True)
class SliceHandle:
    """One physical slice in the inventory."""

    slice_id: str
    accelerator: str
    info: topo.SliceInfo


@dataclasses.dataclass
class GangAssignment:
    """Result of admission: which slices a job got, and the host layout.
    ``host_of(process_id)`` maps a job process to (slice_id, host_index)."""

    job_uid: str
    slices: List[SliceHandle]
    hosts_per_slice: int

    def host_of(self, process_id: int) -> tuple:
        s, h = divmod(process_id, self.hosts_per_slice)
        return self.slices[s].slice_id, h

    @property
    def total_hosts(self) -> int:
        return len(self.slices) * self.hosts_per_slice


class SliceAllocator:
    """Inventory of slices by accelerator type with atomic gang admission.

    ``capacity`` maps accelerator type -> number of identical slices the
    cluster owns (e.g. ``{"v5p-32": 4}``). ``cpu-*`` accelerators are
    treated as unlimited local capacity (the hermetic backend)."""

    def __init__(self, capacity: Optional[Dict[str, int]] = None):
        self._lock = threading.Lock()
        self._free: Dict[str, List[SliceHandle]] = {}
        self._assigned: Dict[str, GangAssignment] = {}
        self._cpu_counter = 0
        for acc, n in (capacity or {}).items():
            info = topo.parse_accelerator(acc)
            self._free[info.accelerator] = [
                SliceHandle(f"{info.accelerator}/slice-{i}", info.accelerator, info)
                for i in range(n)
            ]

    def admit(self, job: TPUJob) -> Optional[GangAssignment]:
        """All-or-nothing: returns an assignment of ``num_slices`` whole
        slices, or None if capacity is short. Idempotent per job uid."""
        uid = job.metadata.uid
        with self._lock:
            if uid in self._assigned:
                return self._assigned[uid]
            info = topo.parse_accelerator(job.spec.tpu.accelerator, job.spec.tpu.topology)
            want = max(job.spec.tpu.num_slices, 1)
            if info.generation == "cpu":
                # Local/hermetic backend: slices are virtual and unlimited,
                # and every replica is a "host" of its virtual slice (cpu
                # jobs aren't bound by physical host counts — validation
                # exempts them too).
                from tfk8s_tpu.api import helpers as _h

                total = max(_h.total_replicas(job), 1)
                hosts_per_slice = -(-total // want)  # ceil div
                handles = []
                for _ in range(want):
                    handles.append(
                        SliceHandle(f"cpu/slice-{self._cpu_counter}", info.accelerator, info)
                    )
                    self._cpu_counter += 1
                ga = GangAssignment(uid, handles, hosts_per_slice=hosts_per_slice)
                self._assigned[uid] = ga
                return ga
            free = self._free.get(info.accelerator, [])
            if len(free) < want:
                return None
            handles = [free.pop() for _ in range(want)]
            ga = GangAssignment(uid, handles, hosts_per_slice=info.hosts)
            self._assigned[uid] = ga
            log.info(
                "admitted job uid=%s onto %s", uid, [h.slice_id for h in handles]
            )
            return ga

    def assignment(self, job_uid: str) -> Optional[GangAssignment]:
        with self._lock:
            return self._assigned.get(job_uid)

    def release(self, job_uid: str) -> None:
        """Return a gang's slices to the pool (job finished, deleted, or
        gang-restarting after slice loss)."""
        with self._lock:
            ga = self._assigned.pop(job_uid, None)
            if ga is None:
                return
            for h in ga.slices:
                if not h.slice_id.startswith("cpu/"):
                    self._free.setdefault(h.accelerator, []).append(h)
            log.info("released gang of job uid=%s", job_uid)

    def free_slices(self, accelerator: str) -> int:
        with self._lock:
            info = topo.parse_accelerator(accelerator)
            return len(self._free.get(info.accelerator, []))
