"""Gang scheduling: all-or-nothing admission of a job's replicas onto
ICI-contiguous TPU sub-slices.

The reference has no equivalent — k8s Jobs admit pods independently
(k8s-operator.md:44-49) and a partially-scheduled TF cluster just wedges.
On TPU the hardware forces the issue: a slice exists or it doesn't, and a
job's mesh spans whole slices. This module is the SURVEY.md §7 hard-part-1
answer, now topology-aware: the inventory is a set of physical slices
whose host grids (utils/topology.py) are carved into axis-aligned BOXES
of host blocks by guillotine splitting, so every admitted gang's hosts
are ICI-contiguous *by construction* (property-tested in
tests/test_topology_placement.py). A job asking for a smaller slice
shape of the same generation (v5p-16 out of a v5p-32 inventory) gets a
contiguous sub-grid rather than a whole fungible slice; releases return
the boxes to the free list.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

from tfk8s_tpu.api.types import TPUJob
from tfk8s_tpu.utils import topology as topo
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("gang")


@dataclasses.dataclass(frozen=True)
class Box:
    """Axis-aligned region of a physical slice's host grid."""

    origin: Tuple[int, ...]
    shape: Tuple[int, ...]

    @property
    def hosts(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    def fits(self, shape: Tuple[int, ...]) -> bool:
        return all(b >= r for b, r in zip(self.shape, shape))


@dataclasses.dataclass(frozen=True)
class PhysicalSlice:
    """One slice in the cluster inventory."""

    slice_id: str
    info: topo.SliceInfo


@dataclasses.dataclass(frozen=True)
class SliceHandle:
    """A job's allocated region: a contiguous box of hosts within one
    physical slice (possibly the whole slice)."""

    slice_id: str  # physical slice id
    accelerator: str  # the REQUESTED accelerator type
    info: topo.SliceInfo  # the requested slice shape
    physical: Optional[PhysicalSlice] = None
    box: Optional[Box] = None

    def global_host_index(self, local_host: int) -> int:
        """Job-local host index -> physical host index in the slice
        (placement for node selectors). Identity when the handle is not
        a carved sub-slice (cpu/hermetic)."""
        if self.physical is None or self.box is None:
            return local_host
        # local coords within the box, C-order over the box shape
        coords = []
        rem = local_host
        for dim in reversed(self.box.shape):
            coords.append(rem % dim)
            rem //= dim
        coords = tuple(reversed(coords))
        phys_coords = tuple(o + c for o, c in zip(self.box.origin, coords))
        return topo.host_index_of(self.physical.info, phys_coords)


@dataclasses.dataclass
class GangAssignment:
    """Result of admission: which slices a job got, and the host layout.
    ``host_of(process_id)`` maps a job process to (slice_id, job-local
    host index); ``global_host_of`` gives the physical host index for
    placement."""

    job_uid: str
    slices: List[SliceHandle]
    hosts_per_slice: int

    def handle_of(self, process_id: int) -> "SliceHandle":
        """The ONE pid -> slice-handle mapping; every consumer (env
        rendering, node selectors) goes through here."""
        return self.slices[process_id // self.hosts_per_slice]

    def host_of(self, process_id: int) -> tuple:
        s, h = divmod(process_id, self.hosts_per_slice)
        return self.slices[s].slice_id, h

    def global_host_of(self, process_id: int) -> int:
        return self.handle_of(process_id).global_host_index(
            process_id % self.hosts_per_slice
        )

    @property
    def total_hosts(self) -> int:
        return len(self.slices) * self.hosts_per_slice


def _guillotine_split(free: Box, want: Tuple[int, ...]) -> Tuple[Box, List[Box]]:
    """Carve a ``want``-shaped box from ``free``'s origin corner;
    remainder returned as new free boxes (one per dim that was cut)."""
    assert free.fits(want)
    remainders = []
    cur = free
    for d in range(len(want)):
        if cur.shape[d] > want[d]:
            # cut along d: keep [0, want_d), free the rest
            rem_origin = tuple(
                o + (want[d] if i == d else 0) for i, o in enumerate(cur.origin)
            )
            rem_shape = tuple(
                (cur.shape[i] - want[d]) if i == d else (
                    want[i] if i < d else cur.shape[i]
                )
                for i in range(len(want))
            )
            remainders.append(Box(rem_origin, rem_shape))
    carved = Box(cur.origin, tuple(want))
    return carved, remainders


class SliceAllocator:
    """Inventory of physical slices with atomic, topology-aware gang
    admission.

    ``capacity`` maps accelerator type -> number of identical slices the
    cluster owns (e.g. ``{"v5p-32": 4}``). Jobs may request the same
    type or a *smaller* slice shape of the same generation; either way
    the allocation is a contiguous box of host blocks. ``cpu-*``
    accelerators are treated as unlimited local capacity (the hermetic
    backend)."""

    def __init__(self, capacity: Optional[Dict[str, int]] = None):
        self._lock = threading.Lock()
        # physical slice id -> (PhysicalSlice, free boxes)
        self._slices: Dict[str, Tuple[PhysicalSlice, List[Box]]] = {}
        self._assigned: Dict[str, GangAssignment] = {}
        self._cpu_counter = 0
        # bumped on every inventory transition (placement / release) so
        # observers (capacity gauges) can skip recomputing when idle
        self.version = 0
        for acc, n in (capacity or {}).items():
            info = topo.parse_accelerator(acc)
            grid = topo.host_grid_shape(info)
            for i in range(n):
                ps = PhysicalSlice(f"{info.accelerator}/slice-{i}", info)
                self._slices[ps.slice_id] = (
                    ps,
                    [Box((0,) * len(grid), grid)],
                )

    # -- admission ----------------------------------------------------------

    def _find_box(self, want_info: topo.SliceInfo) -> Optional[SliceHandle]:
        """Carve one contiguous box shaped like ``want_info``'s host grid
        from any compatible physical slice. Caller holds the lock."""
        want_grid = topo.host_grid_shape(want_info)
        for ps, free in self._slices.values():
            if ps.info.generation != want_info.generation:
                continue
            if len(topo.host_grid_shape(ps.info)) != len(want_grid):
                continue
            # best fit: smallest free box that fits (least fragmentation)
            candidates = [b for b in free if b.fits(want_grid)]
            if not candidates:
                continue
            best = min(candidates, key=lambda b: b.hosts)
            free.remove(best)
            carved, remainders = _guillotine_split(best, want_grid)
            free.extend(remainders)
            return SliceHandle(
                slice_id=f"{ps.slice_id}@{'x'.join(map(str, carved.origin))}",
                accelerator=want_info.accelerator,
                info=want_info,
                physical=ps,
                box=carved,
            )
        return None

    @staticmethod
    def _cpu_hosts_per_slice(job: TPUJob, want: int) -> int:
        from tfk8s_tpu.api import helpers as _h

        return -(-max(_h.total_replicas(job), 1) // want)  # ceil div

    def _assignment_fits(
        self, ga: GangAssignment, job: TPUJob, info: topo.SliceInfo, want: int
    ) -> bool:
        """Does a held assignment still satisfy the job's CURRENT spec?
        False after a scale / accelerator / num_slices edit — the gang
        must be released and re-admitted (slices are whole-gang units;
        there is no partial grow/shrink on TPU hardware)."""
        if len(ga.slices) != want or ga.slices[0].info != info:
            return False
        if info.generation == "cpu":
            return ga.hosts_per_slice == self._cpu_hosts_per_slice(job, want)
        return ga.hosts_per_slice == info.hosts

    def admit(self, job: TPUJob) -> Optional[GangAssignment]:
        """All-or-nothing: returns an assignment of ``num_slices``
        contiguous sub-slices, or None if capacity is short. Idempotent
        per job uid while the spec's demand is unchanged. A demand edit
        (scale, accelerator, num_slices) re-admits atomically: the held
        boxes are offered back to the pool for the new carve, but if the
        new demand cannot be satisfied the old assignment is RESTORED
        intact — the running gang keeps its hosts (no double-booking
        window) and the caller sees None (gang pending)."""
        uid = job.metadata.uid
        with self._lock:
            info = topo.parse_accelerator(job.spec.tpu.accelerator, job.spec.tpu.topology)
            want = max(job.spec.tpu.num_slices, 1)
            held = self._assigned.get(uid)
            if held is not None and self._assignment_fits(held, job, info, want):
                return held
            if held is None:
                ga = self._admit_locked(job, info, want, uid)
                if ga is not None:
                    self._assigned[uid] = ga
                    self.version += 1
                    log.info(
                        "admitted job uid=%s onto %s",
                        uid, [h.slice_id for h in ga.slices],
                    )
                return ga
            # Demand changed. Snapshot the free lists so a failed re-carve
            # restores the world exactly (the held boxes may be needed by,
            # or adjacent to, the new shape — release first, then carve).
            snapshot = self._snapshot_free()
            for h in held.slices:
                self._release_handle(h)
            ga = self._admit_locked(job, info, want, uid)
            if ga is None:
                self._restore_free(snapshot)
                log.debug(
                    "job uid=%s demand change unsatisfiable; keeping old gang",
                    uid,
                )
                return None
            self._assigned[uid] = ga
            self.version += 1
            log.info(
                "job uid=%s demand changed; re-admitted onto %s",
                uid, [h.slice_id for h in ga.slices],
            )
            return ga

    def _admit_locked(
        self, job: TPUJob, info: topo.SliceInfo, want: int, uid: str
    ) -> Optional[GangAssignment]:
        """Carve ``want`` slices for the job, or None (partial carves
        rolled back). Caller holds the lock and owns ``_assigned``."""
        if info.generation == "cpu":
            # Local/hermetic backend: slices are virtual and unlimited,
            # and every replica is a "host" of its virtual slice (cpu
            # jobs aren't bound by physical host counts — validation
            # exempts them too).
            hosts_per_slice = self._cpu_hosts_per_slice(job, want)
            handles = []
            for _ in range(want):
                handles.append(
                    SliceHandle(f"cpu/slice-{self._cpu_counter}", info.accelerator, info)
                )
                self._cpu_counter += 1
            return GangAssignment(uid, handles, hosts_per_slice=hosts_per_slice)

        handles: List[SliceHandle] = []
        for _ in range(want):
            h = self._find_box(info)
            if h is None:
                # all-or-nothing: roll back partial carves
                for got in handles:
                    self._release_handle(got)
                return None
            handles.append(h)
        return GangAssignment(uid, handles, hosts_per_slice=info.hosts)

    def _snapshot_free(self) -> Dict[str, List[Box]]:
        """Copy of every slice's free list (caller holds the lock) — the
        one rollback mechanism shared by admit / admit_with_preemption /
        preemption_plan."""
        return {sid: list(free) for sid, (_ps, free) in self._slices.items()}

    def _restore_free(self, snapshot: Dict[str, List[Box]]) -> None:
        for sid, boxes in snapshot.items():
            ps, _stale = self._slices[sid]
            self._slices[sid] = (ps, boxes)

    def _release_handle(self, h: SliceHandle) -> None:
        if h.physical is None or h.box is None:
            return
        _, free = self._slices[h.physical.slice_id]
        free.append(h.box)
        self._coalesce(free)

    def _coalesce(self, free: List[Box]) -> None:
        """Merge axis-adjacent same-shape boxes so released sub-slices
        recombine into larger allocatable regions."""
        merged = True
        while merged:
            merged = False
            for i in range(len(free)):
                for j in range(i + 1, len(free)):
                    m = _try_merge(free[i], free[j])
                    if m is not None:
                        free[i] = m
                        free.pop(j)
                        merged = True
                        break
                if merged:
                    break

    def assignment(self, job_uid: str) -> Optional[GangAssignment]:
        with self._lock:
            return self._assigned.get(job_uid)

    def admit_with_preemption(
        self, job: TPUJob, victim_uids: List[str]
    ) -> Optional[GangAssignment]:
        """Atomically release ``victim_uids``' gangs and admit ``job``
        into the freed capacity — under ONE lock, so no other job (least
        of all a victim's own concurrent sync re-admitting itself) can
        slip into the window between release and carve. On failure the
        victims' assignments and the free lists are restored intact."""
        uid = job.metadata.uid
        with self._lock:
            info = topo.parse_accelerator(job.spec.tpu.accelerator, job.spec.tpu.topology)
            want = max(job.spec.tpu.num_slices, 1)
            snapshot_free = self._snapshot_free()
            snapshot_assigned = {
                v: self._assigned.get(v) for v in victim_uids
            }
            for v in victim_uids:
                ga_v = self._assigned.pop(v, None)
                if ga_v is not None:
                    for h in ga_v.slices:
                        self._release_handle(h)
            held = self._assigned.pop(uid, None)  # demand-changed re-carve
            if held is not None:
                for h in held.slices:
                    self._release_handle(h)
            ga = self._admit_locked(job, info, want, uid)
            if ga is None:
                self._restore_free(snapshot_free)
                for v, a in snapshot_assigned.items():
                    if a is not None:
                        self._assigned[v] = a
                if held is not None:
                    self._assigned[uid] = held
                return None
            self._assigned[uid] = ga
            self.version += 1
            log.info(
                "admitted job uid=%s onto %s, preempting %s",
                uid, [h.slice_id for h in ga.slices], victim_uids,
            )
            return ga

    def preemption_plan(
        self, job: TPUJob, candidate_uids: List[str]
    ) -> Optional[List[str]]:
        """Dry-run (k8s-preemption style): the SHORTEST prefix of
        ``candidate_uids`` (caller orders them cheapest-victim-first)
        whose release would let ``job`` admit, or None when even evicting
        all of them cannot help — the caller must then evict nobody
        (evicting without a feasible plan would livelock the cluster:
        victims churn forever while the job still never fits). Pure
        simulation: every free-list mutation is rolled back before
        returning."""
        uid = job.metadata.uid
        with self._lock:
            info = topo.parse_accelerator(job.spec.tpu.accelerator, job.spec.tpu.topology)
            want = max(job.spec.tpu.num_slices, 1)
            snapshot = self._snapshot_free()
            try:
                # the real admit() offers the preemptor's own held boxes
                # back for a demand-changed re-carve; the dry run must do
                # the same or a scale-up that needs its own boxes PLUS a
                # victim's is judged infeasible (priority inversion)
                held_self = self._assigned.get(uid)
                if held_self is not None:
                    for h in held_self.slices:
                        self._release_handle(h)
                plan: List[str] = []
                for vuid in candidate_uids:
                    held = self._assigned.get(vuid)
                    if held is None:
                        continue
                    for h in held.slices:
                        self._release_handle(h)
                    plan.append(vuid)
                    ga = self._admit_locked(job, info, want, uid)
                    if ga is not None:
                        # trial carve mutated the free lists; the finally
                        # block restores everything
                        return plan
                return None
            finally:
                self._restore_free(snapshot)

    def release(self, job_uid: str) -> None:
        """Return a gang's boxes to the pool (job finished, deleted, or
        gang-restarting after slice loss)."""
        with self._lock:
            ga = self._assigned.pop(job_uid, None)
            if ga is None:
                return
            for h in ga.slices:
                self._release_handle(h)
            self.version += 1
            log.info("released gang of job uid=%s", job_uid)

    def capacity_summary(self) -> Dict[str, int]:
        """Free whole-slice count per physical accelerator type in the
        inventory — the operator exports these as one labeled gauge on
        /metrics: ``gang_free_slices{accelerator="<type>"}``, e.g.
        ``gang_free_slices{accelerator="v5litepod-16"}``."""
        with self._lock:
            accs = sorted({ps.info.accelerator for ps, _ in self._slices.values()})
        return {acc: self.free_slices(acc) for acc in accs}

    def free_slices(self, accelerator: str) -> int:
        """How many ``accelerator``-shaped sub-slices could be admitted
        right now (counts carvable boxes, not just whole slices)."""
        with self._lock:
            info = topo.parse_accelerator(accelerator)
            grid = topo.host_grid_shape(info)
            n = 0
            for ps, free in self._slices.values():
                if ps.info.generation != info.generation:
                    continue
                if len(topo.host_grid_shape(ps.info)) != len(grid):
                    continue
                for b in free:
                    if b.fits(grid):
                        # how many want-shaped tiles fit in this box
                        tiles = 1
                        for bs, ws in zip(b.shape, grid):
                            tiles *= bs // ws
                        n += tiles
            return n


def _try_merge(a: Box, b: Box) -> Optional[Box]:
    """Merge two boxes iff they are flush along exactly one axis."""
    for d in range(len(a.shape)):
        same_other = all(
            a.origin[i] == b.origin[i] and a.shape[i] == b.shape[i]
            for i in range(len(a.shape))
            if i != d
        )
        if not same_other:
            continue
        if a.origin[d] + a.shape[d] == b.origin[d]:
            return Box(a.origin, tuple(
                a.shape[i] + (b.shape[d] if i == d else 0)
                for i in range(len(a.shape))
            ))
        if b.origin[d] + b.shape[d] == a.origin[d]:
            return Box(b.origin, tuple(
                b.shape[i] + (a.shape[d] if i == d else 0)
                for i in range(len(a.shape))
            ))
    return None
