"""L3 trainer layer (SURVEY.md C18/C19 + gang scheduling): renders a TPUJob
into gang-admitted, topology-placed replica pods/services carrying the JAX
coordination contract, and the TPUJob controller that reconciles them.
"""

from tfk8s_tpu.trainer.gang import GangAssignment, SliceAllocator, SliceHandle  # noqa: F401
from tfk8s_tpu.trainer.tpujob_controller import FINALIZER, TPUJobController  # noqa: F401
from tfk8s_tpu.trainer.serve_controller import SERVE_FINALIZER, TPUServeController  # noqa: F401
from tfk8s_tpu.trainer import labels, replicas  # noqa: F401
