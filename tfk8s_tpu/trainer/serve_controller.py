"""The TPUServe controller: level-triggered reconcile of a TPUServe into a
set of independent serving replica pods, with readiness-gated surge
rolling updates and a queue-depth autoscaler.

Where the TPUJob controller reconciles a *gang* (all-or-nothing, fails as
a unit, scale replaces the whole set), serving replicas are deliberately
independent: each pod holds its own model copy (runtime/server.py), so the
controller can create/drain them one at a time — which is exactly what
makes a zero-downtime rolling update possible.

Reconcile contract (idempotent, every step safe to repeat):

1. missing object -> drop controller-side state (autoscaler EMA, rollout
   spans); deletion timestamp -> finalizer teardown.
2. default + validate; invalid specs -> Degraded(ValidationFailed).
3. compute the desired pod-template hash (task + checkpoint + template +
   batching — runtime/server.template_hash). Pods carry it as a label;
   a hash mismatch makes a pod "old".
4. **Rolling update invariants** (RollingUpdatePolicy), maintained
   level-triggered against the OBSERVED pods, never against remembered
   intent:
   - total live pods <= replicas + max_surge (the surge ceiling);
   - an old pod is deleted only while available (Ready) pods stay >=
     replicas - max_unavailable AFTER the delete (the availability
     floor) — new replicas must pass readiness first, so an update never
     drops below the floor;
   - deletion drains: the kubelet signals the entrypoint's stop event and
     the model server finishes queued requests before exiting
     (runtime/server.serve), so accepted requests never fail.
5. **Readiness**: a replica is Ready once RUNNING *and* its server has
   loaded the checkpoint and reported ``serving_ready`` through the
   kubelet's health/progress publication into pod status — the hermetic
   form of a kubelet readiness probe (the server only reports after the
   weights are resident).
6. **Autoscaler** (AutoscalePolicy): smooth the replicas' reported queue
   depth with an EMA, size replicas to hold per-replica depth near
   target; hysteresis bands + cooldown make it provably non-flapping
   (scale-up needs depth > target*high_band, scale-down needs depth <
   target*low_band, and consecutive scale events are >= cooldown_s
   apart). The controller patches its own spec.replicas (HPA-style).
7. status: replicas/ready/updated counts, observed_version, Available/
   Progressing/Degraded conditions; events; per-serve labeled gauges;
   one trace span per completed rollout.
"""

from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Tuple

from tfk8s_tpu.api import (
    serde,
    set_serve_defaults,
    validate_serve,
)
from tfk8s_tpu.api.helpers import set_serve_condition
from tfk8s_tpu.api.types import (
    ContainerSpec,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodPhase,
    PodSpec,
    RestartPolicy,
    ServeConditionType,
    TPUServe,
)
from tfk8s_tpu.client.clientset import Clientset
from tfk8s_tpu.client.informer import ResourceEventHandler, SharedIndexInformer
from tfk8s_tpu.client.listers import Lister
from tfk8s_tpu.client.store import Conflict, NotFound
from tfk8s_tpu.controller.controller import Controller
from tfk8s_tpu.obs.trace import TRACEPARENT_ENV, Tracer, get_tracer
from tfk8s_tpu.runtime.server import template_hash
from tfk8s_tpu.trainer import labels as L
from tfk8s_tpu.utils.logging import EventRecorder, Metrics, get_logger

log = get_logger("tpuserve")

SERVE_FINALIZER = "tfk8s.dev/serve-cleanup"

# Pods report load every kubelet flush (~1s); re-reconciling on that
# cadence keeps the autoscaler live even when no pod event fires (e.g.
# load drained and reports stopped changing). Monkeypatched down in tests.
AUTOSCALE_PERIOD_S = 1.0
# EMA smoothing factor for the queue-depth signal: ~3 observations to
# cross a band edge, so a single spiky flush can't trigger a scale.
EMA_ALPHA = 0.4
# Disaggregated decode pools scale on SLOT OCCUPANCY (live decode slots /
# slot capacity), not queue depth: a decode pool's backlog shows up as
# full batches long before a queue forms. Target fraction of capacity in
# use; the autoscale bands apply multiplicatively around it.
DECODE_TARGET_OCCUPANCY = 0.75


def _serve_version(serve: TPUServe) -> str:
    """The pod-template hash: everything that, when changed, requires
    replacing replicas (weights ref, code template, batching knobs).
    ``disaggregation`` joins the hash only when PRESENT (existing
    single-pool hashes are unchanged), and only by presence: pool
    COUNTS scale in place like ``spec.replicas`` — adding/removing the
    block itself is what changes the pods' phase env and rolls.
    ``kv_tier`` joins WHOLE when present: its knobs render into the
    pods' env (host-tier byte budget, peer fetch), so a knob edit must
    roll the replicas — unlike the directory TTL, which only the
    gateway reads, but hashing the block uniformly keeps the rule
    simple (the TTL is a tuning knob nobody flips without also
    reconsidering capacity)."""
    base = {
        "task": serve.spec.task,
        "checkpoint": serve.spec.checkpoint,
        "template": serde.to_wire(serve.spec.template),
        "batching": serde.to_wire(serve.spec.batching),
    }
    if serve.spec.disaggregation is not None:
        base["disaggregation"] = True
    if serve.spec.kv_tier is not None:
        base["kv_tier"] = serde.to_wire(serve.spec.kv_tier)
    return template_hash(base)


def serve_pools(serve: TPUServe) -> List[Tuple[str, int]]:
    """The serve's replica pools as ``(phase, desired_count)`` pairs:
    one anonymous pool for a single-pool serve, the labeled
    prefill/decode pair under disaggregation."""
    d = serve.spec.disaggregation
    if d is None:
        return [("", serve.spec.replicas)]
    return [("prefill", d.prefill_replicas), ("decode", d.decode_replicas)]


def pod_phase_of(pod: Pod) -> str:
    """Which pool a serving pod belongs to ("" = the single pool)."""
    return pod.metadata.labels.get(L.SERVE_PHASE, "")


def render_serve_pod(
    serve: TPUServe, version: str, index: int, phase: str = ""
) -> Pod:
    """One serving replica pod at ``version``. Names carry the version so
    surge pods of two template generations coexist during a rollout;
    disaggregated pods also carry their ``phase`` (name, label, and
    ``TFK8S_SERVE_PHASE`` env) so the two pools render, roll, and
    aggregate independently."""
    spec = serve.spec
    tag = f"{phase}-" if phase else ""
    name = f"{serve.metadata.name}-srv-{version}-{tag}{index}"
    tmpl = spec.template
    env = {
        **tmpl.env,
        "TFK8S_SERVE_NAME": serve.metadata.name,
        "TFK8S_NAMESPACE": serve.metadata.namespace,
        "TFK8S_POD_NAME": name,
        "TFK8S_SERVE_TASK": spec.task,
        "TFK8S_SERVE_CHECKPOINT": spec.checkpoint,
        "TFK8S_SERVE_VERSION": version,
        "TFK8S_SERVE_MAX_BATCH": str(spec.batching.max_batch_size),
        "TFK8S_SERVE_BATCH_TIMEOUT_MS": str(spec.batching.batch_timeout_ms),
        "TFK8S_SERVE_QUEUE_LIMIT": str(spec.batching.queue_limit),
        # decode-loop knobs (generative tasks): paged KV-cache geometry
        "TFK8S_SERVE_PAGE_SIZE": str(spec.batching.page_size),
        "TFK8S_SERVE_MAX_PAGES": str(spec.batching.max_pages),
        # token-scheduler knobs (runtime/sched): admission policy,
        # page-spill preemption, speculative decode
        "TFK8S_SERVE_SCHED_POLICY": spec.batching.scheduler.policy,
        "TFK8S_SERVE_PREEMPTION": "1" if spec.batching.scheduler.preemption else "0",
        "TFK8S_SERVE_AGING_S": str(spec.batching.scheduler.aging_s),
        "TFK8S_SERVE_SPEC_DECODE": "1" if spec.batching.scheduler.spec_decode else "0",
        "TFK8S_SERVE_SPEC_TOKENS": str(spec.batching.scheduler.spec_tokens),
        "TFK8S_SERVE_SPEC_DRAFT": spec.batching.scheduler.spec_draft,
    }
    if phase:
        env["TFK8S_SERVE_PHASE"] = phase
    if spec.kv_tier is not None:
        # KV economy (runtime/kvtier): host-tier byte budget + peer
        # fetch render per replica; the directory TTL stays gateway-side
        env["TFK8S_KV_HOST_BYTES"] = str(spec.kv_tier.host_bytes)
        env["TFK8S_KV_PEER_FETCH"] = "1" if spec.kv_tier.peer_fetch else "0"
    lbls = L.serve_version_labels(serve.metadata.name, version)
    lbls[L.REPLICA_INDEX] = str(index)
    if phase:
        lbls[L.SERVE_PHASE] = phase
    return Pod(
        metadata=ObjectMeta(
            name=name,
            namespace=serve.metadata.namespace,
            labels=lbls,
            owner_references=[
                OwnerReference(
                    kind=serve.kind, name=serve.metadata.name,
                    uid=serve.metadata.uid,
                )
            ],
        ),
        spec=PodSpec(
            containers=[
                ContainerSpec(
                    entrypoint=tmpl.entrypoint,
                    image=tmpl.image,
                    command=list(tmpl.command),
                    args=list(tmpl.args),
                    env=env,
                    resources=dict(tmpl.resources),
                )
            ],
            # serving pods are replaced by the controller, never restarted
            # in place: a fresh uid re-runs load()->Ready cleanly
            restart_policy=RestartPolicy.NEVER,
        ),
    )


def pod_is_ready(pod: Pod) -> bool:
    """Readiness gate for rollouts — the ONE shared predicate
    (runtime/server.replica_is_ready), so the controller's availability
    accounting and ServeClient's routing can never disagree."""
    from tfk8s_tpu.runtime.server import replica_is_ready

    return replica_is_ready(pod)


class TPUServeController:
    """Owns the TPUServe/Pod informers and the serving reconcile logic."""

    def __init__(
        self,
        clientset: Clientset,
        recorder: Optional[EventRecorder] = None,
        metrics: Optional[Metrics] = None,
        resync_period: float = 0.0,
        tracer: Optional[Tracer] = None,
    ):
        self.cs = clientset
        self.recorder = recorder or EventRecorder(sink=clientset)
        self.metrics = metrics or Metrics()
        self.tracer = tracer or get_tracer()

        self.serve_informer = SharedIndexInformer(
            clientset.tpuserves(namespace=None), resync_period, name="tpuserve",
            metrics=self.metrics,
        )
        self.pod_informer = SharedIndexInformer(
            clientset.pods(namespace=None), resync_period, name="serve-pod",
            metrics=self.metrics,
        )
        self.serves = Lister(self.serve_informer.indexer, "TPUServe")
        self.pods = Lister(self.pod_informer.indexer, "Pod")

        self.controller = Controller(
            "tpuserve",
            self.sync,
            informers=[self.serve_informer, self.pod_informer],
            recorder=self.recorder,
            metrics=self.metrics,
            kind="TPUServe",
            tracer=self.tracer,
        )
        self.serve_informer.add_event_handler(self.controller.default_handler())
        # Pod events re-key to the owning serve. Progress-only updates are
        # NOT filtered out here (unlike the job controller): the replicas'
        # load reports ARE the autoscaler's input signal.
        self.pod_informer.add_event_handler(ResourceEventHandler(
            on_add=self._enqueue_owner,
            on_update=lambda old, new: self._enqueue_owner(new),
            on_delete=self._enqueue_owner,
        ))
        for mname, help_text in (
            ("tfk8s_serving_ready_replicas",
             "Ready serving replicas per TPUServe."),
            ("tfk8s_serving_replicas", "Live serving replicas per TPUServe."),
            ("tfk8s_serving_desired_replicas",
             "spec.replicas per TPUServe (autoscaler-owned when enabled)."),
            ("tfk8s_serving_smoothed_queue_depth",
             "EMA of the replicas' reported queue depth, per TPUServe."),
            ("tfk8s_serving_rollouts_total",
             "Completed rolling updates (template-hash transitions)."),
            ("tfk8s_serving_scale_events_total",
             "Autoscaler replica changes, by direction."),
            ("tfk8s_serving_pods_created_total",
             "Serving pods created by the reconciler."),
            ("tfk8s_serving_pods_deleted_total",
             "Serving pods deleted by the reconciler."),
            ("tfk8s_serving_pool_ready_replicas",
             "Ready replicas per disaggregated phase pool."),
        ):
            self.metrics.describe(mname, help_text)
        # key -> (ema_queue_depth, ema_qps)
        self._load_ema: Dict[str, Tuple[float, float]] = {}
        # key -> monotonic time of the last autoscale event (cooldown)
        self._last_scale: Dict[str, float] = {}
        # key -> (target_version, start_time) of the rollout in flight
        self._rollout_started: Dict[str, Tuple[str, float]] = {}

    def _enqueue_owner(self, obj) -> None:
        meta = getattr(obj, "obj", obj).metadata  # unwrap DeletedFinalStateUnknown
        name = meta.labels.get(L.SERVE_NAME)
        if name:
            self.controller.enqueue_key(f"{meta.namespace}/{name}")

    def run(self, workers: Optional[int] = None, stop=None, block: bool = True) -> bool:
        from tfk8s_tpu.controller.controller import DEFAULT_SYNC_WORKERS

        return self.controller.run(
            DEFAULT_SYNC_WORKERS if workers is None else workers, stop, block=block
        )

    # ------------------------------------------------------------------ sync

    def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        serve = self.serves.get_by_key(key)
        if serve is None:
            self._forget(key)
            return
        if serve.metadata.deletion_timestamp is not None:
            self._finalize(serve)
            return

        cached_status_wire = serde.to_wire(serve.status)
        serve = set_serve_defaults(serde.roundtrip(serve))  # private mutable copy
        serve._status_baseline = cached_status_wire
        errs = validate_serve(serve)
        if errs:
            changed = set_serve_condition(
                serve.status, ServeConditionType.DEGRADED, True,
                reason="ValidationFailed", message="; ".join(errs),
            )
            if changed:
                self.recorder.event(
                    "TPUServe", key, "ValidationFailed", "; ".join(errs)
                )
                self._write_status(serve)
            return

        if SERVE_FINALIZER not in serve.metadata.finalizers:
            try:
                self.cs.tpuserves(ns).patch(
                    serve.metadata.name,
                    {"metadata": {
                        "resourceVersion": str(serve.metadata.resource_version),
                        "finalizers": serve.metadata.finalizers + [SERVE_FINALIZER],
                    }},
                )
            except Conflict:
                self.controller.enqueue_key(key)
            return  # patched object re-enqueues via the watch

        # -- observe --------------------------------------------------------
        version = _serve_version(serve)
        observed = self.pods.list(ns, L.serve_selector(name))
        terminal = (PodPhase.FAILED, PodPhase.SUCCEEDED, PodPhase.DRAINED)
        live = [
            p for p in observed
            if p.metadata.deletion_timestamp is None
            and p.status.phase not in terminal
        ]
        # Failed/completed/drained serving pods are replaced, not
        # restarted in place: delete the carcass; the create pass below
        # brings a fresh replica (new uid -> clean load()->Ready cycle).
        # DRAINED is the graceful case — the replica honored a reclaim
        # notice, unregistered first, and finished its accepted requests
        # under the rollout availability contract (zero failed requests).
        for p in observed:
            if (
                p.status.phase in terminal
                and p.metadata.deletion_timestamp is None
            ):
                reason = (
                    "ReplicaReclaimed"
                    if p.status.phase == PodPhase.DRAINED
                    else "ReplicaFailed"
                )
                self.recorder.event(
                    "TPUServe", key, reason,
                    f"{p.metadata.name}: {p.status.phase.value} "
                    f"{p.status.message}".strip(),
                )
                self._delete_pod(ns, p.metadata.name)

        new = [p for p in live if p.metadata.labels.get(L.SERVE_VERSION) == version]
        old = [p for p in live if p.metadata.labels.get(L.SERVE_VERSION) != version]
        ready_new = [p for p in new if pod_is_ready(p)]
        ready_old = [p for p in old if pod_is_ready(p)]

        # Desired state is a set of pools: one anonymous pool normally,
        # the prefill/decode pair under disaggregation. Surge ceiling and
        # availability floor are computed over the TOTAL so a serve
        # transitioning single<->disagg still honors the rollout contract.
        pools = serve_pools(serve)
        replicas = sum(count for _, count in pools)
        ru = serve.spec.rolling_update
        floor = max(replicas - ru.max_unavailable, 0)
        ceiling = replicas + ru.max_surge

        # rollout bookkeeping: a version transition — INCLUDING the first
        # deployment (observed_version still empty) — opens a trace span
        # and the Started/Complete event pair
        rolling = bool(old) or serve.status.observed_version != version
        if rolling and self._rollout_started.get(key, ("", 0.0))[0] != version:
            self._rollout_started[key] = (version, time.time())
            self.recorder.event(
                "TPUServe", key, "RolloutStarted",
                f"-> {version} ({len(old)} replica(s) to replace)",
            )

        # -- surge creation: bring up new-version replicas, bounded by the
        #    ceiling; per pool, indices not present among that pool's new
        #    pods are missing (indices are pool-local: prefill-0 and
        #    decode-0 coexist)
        to_create: List[Pod] = []
        for phase, count in pools:
            have_idx = {
                int(p.metadata.labels.get(L.REPLICA_INDEX, "-1"))
                for p in new if pod_phase_of(p) == phase
            }
            for i in range(count):
                if i in have_idx:
                    continue
                if len(live) + len(to_create) >= ceiling:
                    break
                pod = render_serve_pod(serve, version, i, phase=phase)
                with self.tracer.start_span(
                    "pod.create", attributes={"pod": pod.metadata.key}
                ) as sp:
                    # same control->data plane handoff as the trainer: the
                    # replica's kubelet/entrypoint spans continue THIS trace,
                    # so a rollout reads as one tree from CRD edit to Ready
                    if sp.traceparent and pod.spec.containers:
                        pod.spec.containers[0].env[TRACEPARENT_ENV] = sp.traceparent
                to_create.append(pod)
        if to_create:
            created = self.cs.pods(ns).create_many(to_create)
            if created:
                self.metrics.inc(
                    "tfk8s_serving_pods_created_total", float(len(created))
                )

        # -- availability-gated old-replica drain: delete old pods only
        #    while the Ready count stays at/above the floor afterwards.
        #    Not-ready old pods are free to go; ready ones leave one at a
        #    time as new replicas pass readiness.
        available = len(ready_new) + len(ready_old)
        for p in sorted(old, key=lambda p: (pod_is_ready(p), p.metadata.name)):
            cost = 1 if pod_is_ready(p) else 0
            if available - cost < floor:
                break  # availability floor: wait for new replicas to ready up
            self.recorder.event(
                "TPUServe", key, "ReplicaDrained",
                f"{p.metadata.name} (version {p.metadata.labels.get(L.SERVE_VERSION)})",
            )
            self._delete_pod(ns, p.metadata.name)
            available -= cost

        # -- scale-down of excess new-version pods (autoscale down or a
        #    replicas edit): highest indices first. Not-ready extras go
        #    freely; a READY extra is deleted only while the Ready count
        #    stays at/above the (new, smaller) floor afterwards — a
        #    scale-down while a retained pod is still loading must not
        #    take the last serving replicas with it (the retained pod's
        #    readiness unblocks the rest, level-triggered).
        desired_by_phase = dict(pools)
        extra = sorted(
            (p for p in new
             if int(p.metadata.labels.get(L.REPLICA_INDEX, "-1"))
             >= desired_by_phase.get(pod_phase_of(p), 0)),
            key=lambda p: (pod_is_ready(p),
                           -int(p.metadata.labels.get(L.REPLICA_INDEX, "-1"))),
        )
        for p in extra:
            cost = 1 if pod_is_ready(p) else 0
            if cost and available - cost < floor:
                break  # wait for the retained replicas to ready up
            self._delete_pod(ns, p.metadata.name)
            available -= cost

        rollout_done = not old and len(ready_new) >= replicas
        if rollout_done and key in self._rollout_started:
            v, t0 = self._rollout_started.pop(key)
            if v == version:
                self.tracer.record_span(
                    "serve.rollout", start=t0, end=time.time(),
                    attributes={"serve": key, "version": version},
                )
                self.recorder.event(
                    "TPUServe", key, "RolloutComplete", f"version {version}"
                )
                self.metrics.inc("tfk8s_serving_rollouts_total")

        self._autoscale(serve, ready_new + ready_old)
        self._update_status(serve, version, live, new, ready_new, ready_old)

        serve_labels = {"namespace": ns, "serve": name}
        self.metrics.set_gauge(
            "tfk8s_serving_ready_replicas",
            float(len(ready_new) + len(ready_old)), serve_labels,
        )
        self.metrics.set_gauge(
            "tfk8s_serving_replicas", float(len(live)), serve_labels
        )
        self.metrics.set_gauge(
            "tfk8s_serving_desired_replicas", float(replicas), serve_labels
        )
        if serve.spec.disaggregation is not None:
            for phase, _count in pools:
                self.metrics.set_gauge(
                    "tfk8s_serving_pool_ready_replicas",
                    float(sum(1 for p in ready_new + ready_old
                              if pod_phase_of(p) == phase)),
                    {**serve_labels, "phase": phase},
                )

        # keep the loop live: readiness flips and load reports arrive via
        # pod updates, but a quiet system (or an autoscaler waiting out
        # its cooldown) still needs a periodic look
        if serve.spec.autoscale.enabled or not rollout_done:
            self.controller.enqueue_after(key, AUTOSCALE_PERIOD_S)

    # ------------------------------------------------------- autoscaler

    def _autoscale(self, serve: TPUServe, ready_pods: List[Pod]) -> None:
        auto = serve.spec.autoscale
        key = serve.metadata.key
        if not auto.enabled:
            self._load_ema.pop(key, None)
            return
        inst_depth = sum(
            p.status.training.get("serving_queue_depth", 0.0) for p in ready_pods
        )
        inst_qps = sum(
            p.status.training.get("serving_qps", 0.0) for p in ready_pods
        )
        prev_depth, prev_qps = self._load_ema.get(key, (inst_depth, inst_qps))
        ema_depth = EMA_ALPHA * inst_depth + (1 - EMA_ALPHA) * prev_depth
        ema_qps = EMA_ALPHA * inst_qps + (1 - EMA_ALPHA) * prev_qps
        self._load_ema[key] = (ema_depth, ema_qps)
        serve.status.queue_depth = round(ema_depth, 3)
        serve.status.qps = round(ema_qps, 3)
        self.metrics.set_gauge(
            "tfk8s_serving_smoothed_queue_depth", ema_depth,
            {"namespace": serve.metadata.namespace, "serve": serve.metadata.name},
        )

        if serve.spec.disaggregation is not None:
            self._autoscale_pools(serve, ready_pods)
            return

        n = serve.spec.replicas
        if not ready_pods or n < 1:
            return  # no signal yet (or scaled to zero by hand)
        per_replica = ema_depth / max(len(ready_pods), 1)
        want = n
        if per_replica > auto.target_queue_depth * auto.high_band:
            want = min(
                max(n + 1, math.ceil(ema_depth / auto.target_queue_depth)),
                auto.max_replicas,
            )
        elif per_replica < auto.target_queue_depth * auto.low_band:
            want = max(n - 1, auto.min_replicas)
        if want == n:
            return
        now = time.monotonic()
        if now - self._last_scale.get(key, -1e9) < auto.cooldown_s:
            return  # cooldown: the anti-flap guarantee
        direction = "up" if want > n else "down"
        try:
            self.cs.tpuserves(serve.metadata.namespace).patch(
                serve.metadata.name, {"spec": {"replicas": want}}
            )
        except (Conflict, NotFound):
            return  # next periodic pass re-evaluates off fresh state
        self._last_scale[key] = now
        serve.spec.replicas = want  # status write below reflects intent
        serve.status.last_scale_time = time.time()
        self.recorder.event(
            "TPUServe", key, "Scaled",
            f"{direction}: {n} -> {want} (ema queue depth "
            f"{ema_depth:.1f}, target {auto.target_queue_depth}/replica)",
        )
        self.metrics.inc(
            "tfk8s_serving_scale_events_total", 1.0, {"direction": direction}
        )
        log.info("%s: autoscale %s %d -> %d (ema depth %.2f)",
                 key, direction, n, want, ema_depth)

    def _autoscale_pools(self, serve: TPUServe, ready_pods: List[Pod]) -> None:
        """Disaggregated autoscaling: each phase pool sizes off ITS OWN
        signal. Prefill replicas absorb queue wait, so the prefill pool
        runs the standard queue-depth law over prefill pods only; decode
        replicas hold long-lived slots, so the decode pool targets slot
        occupancy (live decode slots vs. slot capacity). One spec patch
        carries both counts (a partial patch could clobber the sibling
        pool on a merge that replaces the nested object)."""
        auto = serve.spec.autoscale
        d = serve.spec.disaggregation
        key = serve.metadata.key

        def _ema(tag: str, inst: float) -> float:
            prev, _ = self._load_ema.get(f"{key}#{tag}", (inst, 0.0))
            val = EMA_ALPHA * inst + (1 - EMA_ALPHA) * prev
            self._load_ema[f"{key}#{tag}"] = (val, 0.0)
            return val

        prefill = [p for p in ready_pods if pod_phase_of(p) == "prefill"]
        decode = [p for p in ready_pods if pod_phase_of(p) == "decode"]

        # prefill: queue depth per ready prefill replica (same law as the
        # single-pool autoscaler, scoped to the pool)
        pq = _ema("prefill", sum(
            p.status.training.get("serving_queue_depth", 0.0) for p in prefill
        ))
        want_p = n_p = d.prefill_replicas
        if prefill and n_p >= 1:
            per = pq / len(prefill)
            if per > auto.target_queue_depth * auto.high_band:
                want_p = min(
                    max(n_p + 1, math.ceil(pq / auto.target_queue_depth)),
                    auto.max_replicas,
                )
            elif per < auto.target_queue_depth * auto.low_band:
                want_p = max(n_p - 1, auto.min_replicas)

        # decode: slot occupancy — live decode slots over the pool's slot
        # capacity (ready replicas x max_batch_size)
        slots = _ema("decode", sum(
            p.status.training.get("serving_live_slots", 0.0) for p in decode
        ))
        cap_per = max(serve.spec.batching.max_batch_size, 1)
        want_d = n_d = d.decode_replicas
        if decode and n_d >= 1:
            occ = slots / (len(decode) * cap_per)
            if occ > DECODE_TARGET_OCCUPANCY * auto.high_band:
                want_d = min(
                    max(n_d + 1,
                        math.ceil(slots / (DECODE_TARGET_OCCUPANCY * cap_per))),
                    auto.max_replicas,
                )
            elif occ < DECODE_TARGET_OCCUPANCY * auto.low_band:
                want_d = max(n_d - 1, auto.min_replicas)

        if want_p == n_p and want_d == n_d:
            return
        now = time.monotonic()
        if now - self._last_scale.get(key, -1e9) < auto.cooldown_s:
            return  # cooldown: the anti-flap guarantee
        try:
            self.cs.tpuserves(serve.metadata.namespace).patch(
                serve.metadata.name,
                {"spec": {"disaggregation": {
                    "prefillReplicas": want_p, "decodeReplicas": want_d,
                }}},
            )
        except (Conflict, NotFound):
            return  # next periodic pass re-evaluates off fresh state
        self._last_scale[key] = now
        d.prefill_replicas, d.decode_replicas = want_p, want_d
        serve.status.last_scale_time = time.time()
        for phase, n, want, why in (
            ("prefill", n_p, want_p, f"ema queue depth {pq:.1f}"),
            ("decode", n_d, want_d, f"ema live slots {slots:.1f}"),
        ):
            if want == n:
                continue
            direction = "up" if want > n else "down"
            self.recorder.event(
                "TPUServe", key, "Scaled",
                f"{phase} {direction}: {n} -> {want} ({why})",
            )
            self.metrics.inc(
                "tfk8s_serving_scale_events_total", 1.0,
                {"direction": direction, "phase": phase},
            )
            log.info("%s: autoscale %s pool %s %d -> %d",
                     key, phase, direction, n, want)

    # ----------------------------------------------------------- status

    def _update_status(
        self,
        serve: TPUServe,
        version: str,
        live: List[Pod],
        new: List[Pod],
        ready_new: List[Pod],
        ready_old: List[Pod],
    ) -> None:
        st = serve.status
        st.replicas = len(live)
        st.ready_replicas = len(ready_new) + len(ready_old)
        st.updated_replicas = len(new)
        base = f"/v1/serve/{serve.metadata.namespace}/{serve.metadata.name}"
        pools = serve_pools(serve)
        if serve.spec.disaggregation is None:
            st.endpoint = base
        else:
            # both phase pools are published; the gateway serves the bare
            # path and splits prefill/decode internally
            st.endpoint = ",".join(f"{base}#{phase}" for phase, _ in pools)
        replicas = sum(count for _, count in pools)
        rollout_done = len(new) == len(live) and len(ready_new) >= replicas
        if rollout_done:
            st.observed_version = version
        if serve.spec.disaggregation is None:
            available = st.ready_replicas >= replicas and replicas > 0
        else:
            # a disaggregated serve needs BOTH pools at strength: a fully
            # ready prefill pool can't cover for an empty decode pool
            ready = ready_new + ready_old
            available = replicas > 0 and all(
                sum(1 for p in ready if pod_phase_of(p) == phase) >= count
                for phase, count in pools
            )
        set_serve_condition(
            st, ServeConditionType.AVAILABLE,
            available,
            reason="AllReplicasReady" if available
            else ("ScaledToZero" if replicas == 0 else "Unavailable"),
            message=f"{st.ready_replicas}/{replicas} ready",
        )
        set_serve_condition(
            st, ServeConditionType.PROGRESSING,
            not rollout_done,
            reason="RollingOut" if not rollout_done else "Complete",
            message=f"version {version}",
        )
        set_serve_condition(st, ServeConditionType.DEGRADED, False, reason="")
        self._write_status(serve)

    def _write_status(self, serve: TPUServe) -> bool:
        """Merge-patch the status subresource, with the deep-compare skip
        the job controller uses (the controller is the sole status owner,
        so the cached wire form is an honest baseline)."""
        wire_status = serde.to_wire(serve.status)
        baseline = getattr(serve, "_status_baseline", None)
        if baseline is not None and wire_status == baseline:
            self.metrics.inc("tfk8s_status_patches_skipped_total")
            return True
        try:
            self.cs.tpuserves(serve.metadata.namespace).patch_status(
                serve.metadata.name, {"status": wire_status}
            )
            serve._status_baseline = wire_status
            return True
        except NotFound:
            return False

    # -------------------------------------------------------- teardown

    def _delete_pod(self, ns: str, name: str) -> None:
        try:
            self.cs.pods(ns).delete(name)
            self.metrics.inc("tfk8s_serving_pods_deleted_total")
        except NotFound:
            pass

    def _forget(self, key: str) -> None:
        self._load_ema.pop(key, None)
        self._last_scale.pop(key, None)
        self._rollout_started.pop(key, None)

    def _finalize(self, serve: TPUServe) -> None:
        key = serve.metadata.key
        ns = serve.metadata.namespace
        for p in self.pods.list(ns, L.serve_selector(serve.metadata.name)):
            if p.metadata.deletion_timestamp is None:
                self._delete_pod(ns, p.metadata.name)
        self._forget(key)
        if SERVE_FINALIZER in serve.metadata.finalizers:
            remaining = [
                f for f in serve.metadata.finalizers if f != SERVE_FINALIZER
            ]
            try:
                # rv precondition: completing the delete off a stale list
                # could drop a foreign finalizer (same rule as the job
                # controller's _finalize)
                self.cs.tpuserves(ns).patch(
                    serve.metadata.name,
                    {"metadata": {
                        "resourceVersion": str(serve.metadata.resource_version),
                        "finalizers": remaining,
                    }},
                )
            except Conflict:
                self.controller.enqueue_key(key)
                return
            except NotFound:
                return
        self.recorder.event("TPUServe", key, "ServeDeleted")
        self.recorder.flush()
        self.metrics.remove_labels(
            {"namespace": ns, "serve": serve.metadata.name}
        )
        self.metrics.remove_labels({"serve": serve.metadata.name})
