"""CLI entrypoint — SURVEY.md C1 (`tf_operator/main.go`; sequence
Main → Option → flags → initlog → Run server, images/tf2.png).

Subcommands:

- ``operator``  run the reconcile server (the reference's only mode);
                with ``--kubeconfig`` it reconciles against a remote
                apiserver across a process boundary
- ``run``       end-to-end local demo: operator + kubelet in-process,
                submit one TPUJob, wait for a terminal condition
- ``train``     run a model entrypoint directly in this process (the
                data-plane launcher, no control plane — for debugging)
- ``apiserver`` serve the cluster store over HTTP (client/apiserver.py)
                — the L0 substrate as its own process
- ``kubelet``   run the pod executor as its own process against a remote
                apiserver (the node-agent half of the process split)
- ``submit`` / ``get`` / ``describe`` / ``delete``  the kubectl verbs of
                the reference workflow (k8s-operator.md:33-34 REST paths,
                :50-52 ``kubectl get pod``), driven over the same remote
                client the operator uses
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
from typing import List, Optional

from tfk8s_tpu.cmd.options import Options
from tfk8s_tpu.utils.logging import get_logger, init_logging

log = get_logger("main")


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tfk8s-tpu",
        description="TPU-native TFJob-style training operator",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_op = sub.add_parser("operator", help="run the operator server")
    Options.add_flags(p_op)

    p_run = sub.add_parser("run", help="run one TPUJob end-to-end locally")
    Options.add_flags(p_run)
    p_run.add_argument("--file", default="",
                       help="TPUJob manifest (YAML or JSON); overrides the flag-built spec")
    p_run.add_argument("--name", default="job")
    p_run.add_argument("--entrypoint", default="",
                       help='e.g. "tfk8s_tpu.models.mlp:train" (required without --file)')
    p_run.add_argument("--replicas", type=int, default=1)
    p_run.add_argument("--accelerator", default="cpu-1")
    p_run.add_argument("--env", default="{}",
                       help="extra pod env as JSON")
    p_run.add_argument("--timeout", type=float, default=600.0)

    p_tr = sub.add_parser("train", help="run a model entrypoint in-process")
    p_tr.add_argument("--entrypoint", required=True)
    p_tr.add_argument("--env", default="{}")

    p_api = sub.add_parser("apiserver", help="serve the cluster store over HTTP(S)")
    p_api.add_argument("--host", default="127.0.0.1")
    p_api.add_argument("--port", type=int, default=8443)
    p_api.add_argument("--write-kubeconfig", default="", dest="write_kubeconfig",
                       help="write a kubeconfig JSON for the bound address "
                       "(use with --port 0 to discover the ephemeral port); "
                       "with --self-signed/--token-file it embeds the CA "
                       "and first token")
    p_api.add_argument("--tls-cert", default="", help="server certificate (PEM)")
    p_api.add_argument("--tls-key", default="", help="server private key (PEM)")
    p_api.add_argument("--client-ca", default="",
                       help="CA bundle for verifying client certs (mTLS)")
    p_api.add_argument("--self-signed", default="", metavar="DIR",
                       help="mint a CA + server cert into DIR and serve TLS "
                       "(dev/test; overrides --tls-cert/--tls-key)")
    p_api.add_argument("--journal-dir", default="", dest="journal_dir",
                       help="directory for the write-ahead log + snapshot; "
                            "cluster state survives apiserver restarts "
                            "(empty = in-memory only)")
    p_api.add_argument("--no-fsync", action="store_true", dest="no_fsync",
                       help="journal without fsync (kill-9 safe via page "
                            "cache, not power-loss safe)")
    p_api.add_argument("--token-file", default="",
                       help="static token file 'token,user[,readonly]' per "
                       "line; enables authentication (anonymous -> 401)")

    p_kl = sub.add_parser("kubelet", help="run the pod executor against a remote apiserver")
    p_kl.add_argument("--kubeconfig", required=True)
    p_kl.add_argument("--name", default="kubelet-0",
                      help="node name recorded in pod status")

    # every scheme kind is reachable through the generic verbs; deriving
    # the choice list from the apiserver's plural table means a newly
    # registered kind is a one-line change (and the wire-conformance test
    # fails loudly if the tables ever drift apart)
    from tfk8s_tpu.client.apiserver import PLURALS

    kind_choices = tuple(sorted(PLURALS))

    def kubectlish(name, help_):
        p = sub.add_parser(name, help=help_)
        p.add_argument("--kubeconfig", required=True)
        p.add_argument("-n", "--namespace", default="default")
        return p

    p_sub = kubectlish("submit", "create an object from a manifest "
                                 "(any scheme kind: TPUJob, TPUServe, ...)")
    p_sub.add_argument("--file", required=True,
                       help="manifest (YAML or JSON)")

    p_get = kubectlish("get", "list objects of a kind (or one by name)")
    p_get.add_argument("name", nargs="?", default="")
    p_get.add_argument("-o", "--output", choices=("table", "json"),
                       default="table")
    p_get.add_argument("--kind", default="tpujobs", choices=kind_choices)
    p_get.add_argument("-l", "--selector", default="",
                       help="label selector, e.g. a=b,c=d")
    p_get.add_argument("-w", "--watch", action="store_true",
                       help="after listing, stream changes (kubectl get -w)")
    p_get.add_argument("--watch-timeout", type=float, default=0.0,
                       help="stop watching after N seconds (0 = forever)")

    p_patch = kubectlish("patch", "merge-patch fields of one object "
                                  "(kubectl patch parity; RFC 7386)")
    p_patch.add_argument("name")
    p_patch.add_argument("-p", "--patch", required=True,
                         help='merge patch as JSON, e.g. '
                              '\'{"spec": {"runPolicy": {"suspend": true}}}\'')
    p_patch.add_argument("--kind", default="tpujobs", choices=kind_choices)
    p_patch.add_argument("--subresource", default="",
                         choices=("", "status"),
                         help="patch the status subresource instead")

    p_desc = kubectlish("describe", "full detail of one object + its events")
    p_desc.add_argument("name")
    p_desc.add_argument("--kind", default="tpujobs", choices=kind_choices)

    p_del = kubectlish("delete", "delete an object (finalizer-honoring)")
    p_del.add_argument("name")
    p_del.add_argument("--kind", default="tpujobs", choices=kind_choices)

    p_logs = kubectlish("logs", "print a pod's captured log tail")
    p_logs.add_argument("name", nargs="?", default="",
                        help="pod name (omit with --job to dump the whole job)")
    p_logs.add_argument("--job", default="",
                        help="print logs for every pod of this TPUJob")
    p_logs.add_argument("-f", "--follow", action="store_true",
                        help="stream new lines until the pod terminates "
                        "(single-pod form only)")
    p_logs.add_argument("--follow-timeout", type=float, default=0.0,
                        help="stop following after N seconds (0 = until "
                        "the pod terminates)")

    p_scale = kubectlish("scale", "change a TPUJob's replica count")
    p_scale.add_argument("name")
    p_scale.add_argument("--replicas", type=int, required=True)
    p_scale.add_argument("--replica-type", default="Worker",
                         help="which replica set to scale (default Worker)")

    p_apply = kubectlish("apply", "create or update a TPUJob from a manifest")
    p_apply.add_argument("--file", required=True,
                         help="TPUJob manifest (YAML or JSON)")

    p_sus = kubectlish("suspend", "evict a TPUJob's gang, freeing its slices")
    p_sus.add_argument("name")
    p_res = kubectlish("resume", "re-admit a suspended TPUJob (checkpoint resume)")
    p_res.add_argument("name")
    return parser


def _cmd_operator(opts: Options) -> int:
    from tfk8s_tpu.cmd.server import Server

    stop = threading.Event()
    server = Server(opts)
    try:
        server.run(stop, block=True)
    except KeyboardInterrupt:
        log.info("interrupted; shutting down")
    finally:
        stop.set()
        server.shutdown()
    return 0


def _cmd_run(opts: Options, args: argparse.Namespace) -> int:
    import time

    from tfk8s_tpu.api import helpers
    from tfk8s_tpu.api.types import (
        ContainerSpec, JobConditionType, ObjectMeta, ReplicaSpec, ReplicaType,
        RunPolicy, SchedulingPolicy, TPUJob, TPUJobSpec, TPUSpec,
    )
    from tfk8s_tpu.cmd.server import Server

    if args.file:
        job = load_manifest(args.file)
        if job.metadata.namespace != opts.namespace:
            log.warning(
                "run: overriding manifest namespace %r with --namespace %r",
                job.metadata.namespace, opts.namespace,
            )
            job.metadata.namespace = opts.namespace
    elif args.entrypoint:
        job = TPUJob(
            metadata=ObjectMeta(name=args.name, namespace=opts.namespace),
            spec=TPUJobSpec(
                replica_specs={
                    ReplicaType.WORKER: ReplicaSpec(
                        replicas=args.replicas,
                        template=ContainerSpec(
                            entrypoint=args.entrypoint,
                            env=json.loads(args.env or "{}"),
                        ),
                    )
                },
                tpu=TPUSpec(accelerator=args.accelerator),
                run_policy=RunPolicy(scheduling=SchedulingPolicy(gang=True)),
            ),
        )
    else:
        log.error("run: one of --file or --entrypoint is required")
        return 2

    stop = threading.Event()
    server = Server(opts)
    server.run(stop, block=False)
    name = job.metadata.name
    server.clientset.tpujobs(opts.namespace).create(job)
    log.info("submitted %s/%s; waiting for completion", opts.namespace, name)

    deadline = time.time() + args.timeout
    code = 1
    while time.time() < deadline:
        try:
            cur = server.clientset.tpujobs(opts.namespace).get(name)
        except Exception:
            time.sleep(0.2)
            continue
        if helpers.has_condition(cur.status, JobConditionType.SUCCEEDED):
            log.info("job succeeded")
            code = 0
            break
        if helpers.has_condition(cur.status, JobConditionType.FAILED):
            cond = helpers.get_condition(cur.status, JobConditionType.FAILED)
            log.error("job failed: %s — %s", cond.reason, cond.message)
            code = 1
            break
        time.sleep(0.2)
    else:
        log.error("timed out after %.0fs", args.timeout)
    stop.set()
    server.shutdown()
    return code


def load_manifest(path: str):
    """Decode a TPUJob (or any scheme kind) from a YAML/JSON manifest."""
    from tfk8s_tpu.api import serde

    with open(path) as f:
        text = f.read()
    try:
        import yaml

        try:
            data = yaml.safe_load(text)
        except yaml.YAMLError as exc:
            raise ValueError(f"{path}: malformed YAML: {exc}") from exc
    except ImportError:  # pragma: no cover — pyyaml is baked in
        data = json.loads(text)
    if not isinstance(data, dict):
        raise ValueError(
            f"{path}: manifest must be a mapping, got {type(data).__name__}"
        )
    return serde.decode_object(data)


def _cmd_train(args: argparse.Namespace) -> int:
    _maybe_force_platform()
    from tfk8s_tpu.runtime import registry

    fn = registry.resolve(args.entrypoint)
    registry.call(fn, json.loads(args.env or "{}"), threading.Event())
    return 0


def _cmd_apiserver(args: argparse.Namespace) -> int:
    from tfk8s_tpu.client.apiserver import APIServer, AuthConfig, TLSServerConfig
    from tfk8s_tpu.client.store import ClusterStore

    tls = None
    ca_pem = ""
    if args.self_signed:
        # dev PKI: mint CA + server cert (SANs cover the bind host) so a
        # secured cluster comes up with one flag — kubeadm-init parity
        from tfk8s_tpu.client.tlsutil import generate_ca, issue_cert

        ca = generate_ca()
        sans = [args.host] if args.host not in ("127.0.0.1", "localhost") else []
        sans += ["127.0.0.1", "localhost"]
        server_pair = issue_cert(ca, "tfk8s-apiserver", sans=sans)
        ca_cert_path, _ = ca.write(args.self_signed, "ca")
        cert_path, key_path = server_pair.write(args.self_signed, "apiserver")
        tls = TLSServerConfig(cert_path, key_path, client_ca_file=ca_cert_path)
        ca_pem = ca.cert_pem.decode()
    elif args.tls_cert or args.tls_key or args.client_ca:
        # half a TLS config must be a startup error, never a silent
        # downgrade to plaintext (tokens would go over the wire in clear)
        if not (args.tls_cert and args.tls_key):
            log.error("--tls-cert and --tls-key must be given together "
                      "(got cert=%r key=%r)", args.tls_cert, args.tls_key)
            return 2
        tls = TLSServerConfig(
            args.tls_cert, args.tls_key, client_ca_file=args.client_ca or None
        )
        if args.client_ca:
            with open(args.client_ca) as f:
                ca_pem = f.read()
    auth = AuthConfig.from_token_file(args.token_file) if args.token_file else None
    if auth is not None and tls is None:
        # same rule as the half-TLS case: bearer tokens over plaintext
        # HTTP are sniffable — a silent downgrade must be a startup error
        log.error("--token-file requires TLS (--tls-cert/--tls-key or "
                  "--self-signed): refusing to accept bearer tokens over "
                  "plaintext HTTP")
        return 2

    # the embedded credential must be able to WRITE (a kubelet or
    # operator bootstrapped from this kubeconfig creates pods); resolve
    # it BEFORE binding the listener so the error path leaks no socket
    rw_token = None
    if args.write_kubeconfig and auth and auth.tokens:
        rw_token = next(
            (t for t, u in auth.tokens.items() if not u.readonly), None
        )
        if rw_token is None:
            log.error("--write-kubeconfig: token file has only "
                      "readonly credentials; nothing usable to embed")
            return 2

    from tfk8s_tpu.utils.logging import Metrics

    metrics = Metrics()
    store = ClusterStore(
        journal_dir=args.journal_dir or None,
        fsync=not args.no_fsync,
        # watch-coalescing counter rides the apiserver's own /metrics
        metrics=metrics,
    )
    if args.journal_dir:
        log.info(
            "journal: %s (replayed to rv %d)", args.journal_dir, store.resource_version
        )
    server = APIServer(
        store, host=args.host, port=args.port, tls=tls, auth=auth,
        metrics=metrics,
    )
    if args.write_kubeconfig:
        kc: dict = {"server": server.url}
        if ca_pem:
            kc["certificate_authority_data"] = ca_pem
        if rw_token is not None:
            kc["token"] = rw_token
        with open(args.write_kubeconfig, "w") as f:
            json.dump(kc, f)
    log.info("apiserver listening on %s", server.url)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        log.info("interrupted; shutting down")
    finally:
        server.shutdown()
    return 0


def _maybe_force_platform() -> None:
    """Honor ``TFK8S_JAX_PLATFORM`` before first backend use (subprocess-
    spawned data-plane processes can't rely on env vars alone — see
    runtime.launcher.force_platform)."""
    import os

    plat = os.environ.get("TFK8S_JAX_PLATFORM", "")
    if plat:
        from tfk8s_tpu.runtime.launcher import force_platform

        force_platform(plat)


def _cmd_kubelet(args: argparse.Namespace) -> int:
    _maybe_force_platform()
    from tfk8s_tpu.client.remote import clientset_from_kubeconfig
    from tfk8s_tpu.runtime.kubelet import LocalKubelet

    cs = clientset_from_kubeconfig(args.kubeconfig)
    kubelet = LocalKubelet(cs, name=args.name)
    stop = threading.Event()
    log.info("kubelet %s watching pods via %s", args.name, args.kubeconfig)
    try:
        kubelet.run(stop)
        stop.wait()
    except KeyboardInterrupt:
        log.info("interrupted; shutting down")
    finally:
        stop.set()
    return 0


def _job_phase(job) -> str:
    """Last True condition wins. Correct because ``helpers.set_condition``
    keeps exclusive conditions (Running/Succeeded/Failed/Restarting)
    mutually exclusive — at most one is True at a time."""
    for cond in reversed(job.status.conditions):
        if cond.status:
            return str(getattr(cond.type, "value", cond.type))
    return "Pending"


def _age(ts) -> str:
    import time

    if not ts:
        return "-"
    s = max(0, int(time.time() - ts))
    if s < 120:
        return f"{s}s"
    if s < 7200:
        return f"{s // 60}m"
    return f"{s // 3600}h"


def _load_job_for_namespace(args: argparse.Namespace, verb: str):
    """Shared by submit/apply: load the manifest and apply the -n
    override. -n always wins (matching _cmd_run): a manifest omitting
    the field decodes to "default", so "was it set?" is undetectable —
    warn only when the manifest visibly disagrees."""
    job = load_manifest(args.file)
    if job.metadata.namespace != args.namespace:
        log.warning(
            "%s: overriding manifest namespace %r with --namespace %r",
            verb, job.metadata.namespace, args.namespace,
        )
        job.metadata.namespace = args.namespace
    return job


def _cmd_submit(args: argparse.Namespace) -> int:
    from tfk8s_tpu.client.apiserver import KIND_TO_PLURAL
    from tfk8s_tpu.client.remote import clientset_from_kubeconfig

    cs = clientset_from_kubeconfig(args.kubeconfig)
    obj = _load_job_for_namespace(args, "submit")
    # generic by the manifest's own kind: `submit --file gpt-serve.yaml`
    # creates a TPUServe through the same verb
    created = cs.generic(obj.kind, obj.metadata.namespace).create(obj)
    singular = KIND_TO_PLURAL.get(created.kind, created.kind.lower() + "s")[:-1]
    print(f"{singular} {created.metadata.namespace}/{created.metadata.name} created")
    return 0


def _cmd_get(args: argparse.Namespace) -> int:
    from tfk8s_tpu.api import serde
    from tfk8s_tpu.client.apiserver import PLURALS, parse_selector
    from tfk8s_tpu.client.remote import clientset_from_kubeconfig

    cs = clientset_from_kubeconfig(args.kubeconfig)
    client = cs.generic(PLURALS[args.kind], args.namespace)
    selector = parse_selector(getattr(args, "selector", ""))
    if args.name:
        objs = [client.get(args.name)]
        rv = objs[0].metadata.resource_version
    else:
        objs, rv = client.list(label_selector=selector or None)
    if args.output == "json":
        print(json.dumps([serde.to_wire(o) for o in objs], indent=2))
        if getattr(args, "watch", False):
            return _stream_watch(client, args, rv, selector)
        return 0
    if args.kind == "tpujobs":
        rows = [("NAME", "PHASE", "RESTARTS", "AGE")] + [
            (
                j.metadata.name,
                _job_phase(j),
                str(j.status.gang_restarts),
                _age(j.metadata.creation_timestamp),
            )
            for j in objs
        ]
    elif args.kind == "tpuserves":
        rows = [("NAME", "READY", "UPDATED", "VERSION", "AGE")] + [
            (
                s.metadata.name,
                f"{s.status.ready_replicas}/{s.spec.replicas}",
                str(s.status.updated_replicas),
                s.status.observed_version or "-",
                _age(s.metadata.creation_timestamp),
            )
            for s in objs
        ]
    elif args.kind == "events":
        rows = [("LAST SEEN", "REASON", "OBJECT", "COUNT", "MESSAGE")] + [
            (
                _age(e.last_timestamp),
                e.reason,
                f"{e.involved_kind}/{e.involved_key}",
                str(e.count),
                e.message[:60],
            )
            for e in sorted(objs, key=lambda e: e.last_timestamp or 0)
        ]
    else:
        def phase_of(o) -> str:
            status = getattr(o, "status", None)  # Services carry no status
            phase = getattr(status, "phase", "") if status is not None else ""
            return str(getattr(phase, "value", phase)) or "-"

        rows = [("NAME", "PHASE", "AGE")] + [
            (o.metadata.name, phase_of(o), _age(o.metadata.creation_timestamp))
            for o in objs
        ]
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
    if getattr(args, "watch", False):
        return _stream_watch(client, args, rv, selector)
    return 0


def _stream_watch(
    client, args: argparse.Namespace, since_rv: int, selector=None
) -> int:
    """`kubectl get -w` parity: after the initial table, stream one line
    per change event from the apiserver's watch endpoint (the same
    List-then-Watch contract the reflector uses, images/informer1.png)
    until interrupted or --watch-timeout elapses. The `-l` selector that
    filtered the table filters the stream too (client-side — the watch
    endpoint streams the whole kind)."""
    import time as _time

    from tfk8s_tpu.api import serde
    from tfk8s_tpu.client.store import match_labels

    def phase_of(o) -> str:
        status = getattr(o, "status", None)
        phase = getattr(status, "phase", "") if status is not None else ""
        if args.kind == "tpujobs":
            phase = _job_phase(o)
        return str(getattr(phase, "value", phase)) or "-"

    w = client.watch(since_rv=since_rv)
    deadline = (
        _time.time() + args.watch_timeout if args.watch_timeout else None
    )
    try:
        while deadline is None or _time.time() < deadline:
            ev = w.next(timeout=0.5)
            if ev is None:
                continue
            if ev.object.metadata.namespace != args.namespace:
                continue
            if args.name and ev.object.metadata.name != args.name:
                continue
            if selector and not match_labels(selector, ev.object.metadata.labels):
                continue
            if args.output == "json":
                print(
                    json.dumps(
                        {"type": ev.type.value,
                         "object": serde.to_wire(ev.object)}
                    ),
                    flush=True,
                )
            else:
                print(
                    f"{ev.type.value:<9} {ev.object.metadata.name}  "
                    f"{phase_of(ev.object)}",
                    flush=True,
                )
    except KeyboardInterrupt:
        pass
    finally:
        w.stop()
    return 0


def _cmd_describe(args: argparse.Namespace) -> int:
    from tfk8s_tpu.api import serde
    from tfk8s_tpu.client.apiserver import PLURALS
    from tfk8s_tpu.client.remote import clientset_from_kubeconfig

    cs = clientset_from_kubeconfig(args.kubeconfig)
    kind = PLURALS[getattr(args, "kind", "tpujobs")]
    obj = cs.generic(kind, args.namespace).get(args.name)
    print(json.dumps(serde.to_wire(obj), indent=2))
    # kubectl-describe parity: the object's event history, read from the
    # cluster's mirrored Event objects (operator EventRecorder sink)
    key = f"{args.namespace}/{args.name}"
    events, _rv = cs.generic("Event", args.namespace).list()
    mine = sorted(
        (e for e in events if e.involved_key == key),
        key=lambda e: e.last_timestamp or 0,
    )
    if mine:
        print("\nEvents:")
        for e in mine:
            print(
                f"  {_age(e.last_timestamp):>9}  {e.reason:<22} x{e.count}"
                + (f"  {e.message}" if e.message else "")
            )
    return 0


def _cmd_scale(args: argparse.Namespace) -> int:
    """The reference's 扩容 capability (k8s-operator.md:1) as a verb:
    edit the replica count; the controller re-admits the gang and
    replaces stale-env pods (trainer/tpujob_controller.py). TPU-type jobs
    couple replicas to slice shape, so the apiserver may 422 a count the
    accelerator cannot host — surfaced as-is."""
    from tfk8s_tpu.api.types import ReplicaType
    from tfk8s_tpu.client.remote import clientset_from_kubeconfig

    cs = clientset_from_kubeconfig(args.kubeconfig)
    try:
        rtype = ReplicaType(args.replica_type)
    except ValueError:
        log.error("scale: unknown replica type %r (use %s)",
                  args.replica_type, [t.value for t in ReplicaType])
        return 1
    job = cs.tpujobs(args.namespace).get(args.name)
    if rtype not in job.spec.replica_specs:
        log.error("scale: job %s has no %s replica set",
                  args.name, rtype.value)
        return 1
    # merge-patch touches ONLY the replica count — no resourceVersion, no
    # conflict with the operator's concurrent status writes
    cs.tpujobs(args.namespace).patch(
        args.name,
        {"spec": {"replicaSpecs": {rtype.value: {"replicas": args.replicas}}}},
    )
    print(f"tpujob {args.namespace}/{args.name} scaled: "
          f"{rtype.value}={args.replicas}")
    return 0


def _set_suspend(args: argparse.Namespace, value: bool) -> int:
    from tfk8s_tpu.client.remote import clientset_from_kubeconfig

    cs = clientset_from_kubeconfig(args.kubeconfig)
    job = cs.tpujobs(args.namespace).get(args.name)
    if job.spec.run_policy.suspend == value:
        print(f"tpujob {args.namespace}/{args.name} already "
              f"{'suspended' if value else 'running'}")
        return 0
    # merge-patch on the one field this verb owns — conflict-free by
    # construction
    cs.tpujobs(args.namespace).patch(
        args.name, {"spec": {"runPolicy": {"suspend": value}}}
    )
    print(f"tpujob {args.namespace}/{args.name} "
          f"{'suspended' if value else 'resumed'}")
    return 0


def _cmd_suspend(args: argparse.Namespace) -> int:
    return _set_suspend(args, True)


def _cmd_resume(args: argparse.Namespace) -> int:
    return _set_suspend(args, False)


def _cmd_apply(args: argparse.Namespace) -> int:
    """kubectl-apply parity: create the manifest's job, or PATCH its spec
    when it already exists. The patch is computed as the exact diff
    (replace_patch): fields removed from the manifest get explicit nulls,
    so apply keeps REPLACE semantics over the conflict-free merge-patch
    verb — no resourceVersion, no retry loop (status stays untouched by
    the subresource isolation on the server)."""
    from tfk8s_tpu.api import serde
    from tfk8s_tpu.client.remote import clientset_from_kubeconfig
    from tfk8s_tpu.client.store import AlreadyExists, NotFound, replace_patch

    cs = clientset_from_kubeconfig(args.kubeconfig)
    job = _load_job_for_namespace(args, "apply")
    client = cs.tpujobs(args.namespace)
    for _ in range(2):  # second pass only for the delete/create races
        try:
            client.create(job)
            print(f"tpujob {args.namespace}/{job.metadata.name} created")
            return 0
        except AlreadyExists:
            pass
        try:
            current = client.get(job.metadata.name)
            # default the manifest locally before diffing: current is
            # already defaulted, so an undefaulted desired spec would diff
            # (and null) every server-filled field just for admission to
            # put it back — and "unchanged" would never trigger
            from tfk8s_tpu.api import set_defaults

            set_defaults(job)
            patch = replace_patch(
                serde.to_wire(current.spec), serde.to_wire(job.spec)
            )
            if not patch:
                print(f"tpujob {args.namespace}/{job.metadata.name} unchanged")
                return 0
            client.patch(job.metadata.name, {"spec": patch})
            print(f"tpujob {args.namespace}/{job.metadata.name} configured")
            return 0
        except NotFound:  # deleted since AlreadyExists; loop recreates
            continue
    log.error("apply: object is churning (concurrent delete/create); try again")
    return 1


def _cmd_delete(args: argparse.Namespace) -> int:
    from tfk8s_tpu.client.apiserver import PLURALS
    from tfk8s_tpu.client.remote import clientset_from_kubeconfig

    cs = clientset_from_kubeconfig(args.kubeconfig)
    plural = getattr(args, "kind", "tpujobs")
    cs.generic(PLURALS[plural], args.namespace).delete(args.name)
    print(f"{plural[:-1]} {args.namespace}/{args.name} deleted")
    return 0


def _cmd_patch(args: argparse.Namespace) -> int:
    """`kubectl patch` parity: an RFC 7386 merge patch straight to the
    wire verb — touch only the fields named; no resourceVersion, no
    read-modify-write. The server runs admission on the merged object
    (422 on an invalid result)."""
    from tfk8s_tpu.client.apiserver import PLURALS
    from tfk8s_tpu.client.remote import clientset_from_kubeconfig

    cs = clientset_from_kubeconfig(args.kubeconfig)
    try:
        patch = json.loads(args.patch)
    except ValueError as e:
        log.error("patch: --patch is not valid JSON: %s", e)
        return 1
    if not isinstance(patch, dict):
        log.error("patch: --patch must be a JSON object, got %s",
                  type(patch).__name__)
        return 1
    # catch silently-dropped fields before reporting success: subresource
    # isolation applies EXACTLY ONE side of the object per call, so any
    # key on the wrong side of the split would vanish while the CLI
    # printed "patched"
    if args.subresource == "status":
        if "status" not in patch:
            log.error(
                "patch: --subresource status expects the wrapper form "
                '\'{"status": {...}}\'; this patch would apply nothing'
            )
            return 1
        # envelope keys are server-honored on status patches
        # (apiVersion/kind are the wire envelope; within metadata ONLY
        # resourceVersion — the optimistic precondition — is read) —
        # every genuinely-dropped key is rejected
        extras = sorted(
            set(patch) - {"status", "metadata", "apiVersion", "kind"}
        )
        meta = patch.get("metadata") or {}
        if not isinstance(meta, dict):
            log.error(
                "patch: metadata must be a JSON object, got %s",
                type(meta).__name__,
            )
            return 1
        meta_extras = sorted(set(meta) - {"resourceVersion"})
        if extras or meta_extras:
            dropped = extras + [f"metadata.{k}" for k in meta_extras]
            log.error(
                "patch: --subresource status applies ONLY the status "
                "subtree (+ the metadata.resourceVersion precondition); "
                "%s would be silently dropped — patch them in a separate "
                "call without --subresource", dropped,
            )
            return 1
    elif "status" in patch:
        log.error(
            "patch: status is a subresource and would be dropped by "
            "subresource isolation — patch it in a separate call with "
            "--subresource status"
        )
        return 1
    kind = PLURALS[args.kind]
    client = cs.generic(kind, args.namespace)
    if args.subresource == "status":
        out = client.patch_status(args.name, patch)
    else:
        out = client.patch(args.name, patch)
    sub = "/status" if args.subresource else ""
    print(
        f"{args.kind[:-1]} {args.namespace}/{args.name}{sub} patched "
        f"(rv {out.metadata.resource_version})"
    )
    return 0


def _cmd_logs(args: argparse.Namespace) -> int:
    """`kubectl logs` parity: the tail rides pod status (captured by the
    kubelet, PodStatus.log_tail), so reading it is a plain GET — no
    kubelet proxy endpoint needed, unlike real k8s."""
    from tfk8s_tpu.client.remote import clientset_from_kubeconfig
    from tfk8s_tpu.trainer import labels as L

    cs = clientset_from_kubeconfig(args.kubeconfig)
    if bool(args.name) == bool(args.job):
        log.error("logs: pass exactly one of POD_NAME or --job JOB")
        return 1
    if getattr(args, "follow", False) and args.job:
        log.error("logs: --follow works with a single POD_NAME")
        return 1
    if args.name:
        pods = [cs.pods(args.namespace).get(args.name)]
    else:
        pods, _rv = cs.pods(args.namespace).list(
            label_selector=L.job_selector(args.job)
        )
        if not pods:
            log.error("logs: no pods found for job %r", args.job)
            return 1
    for pod in sorted(pods, key=lambda p: p.metadata.name):
        if args.job:
            print(f"==> {pod.metadata.namespace}/{pod.metadata.name} "
                  f"({pod.status.phase.value}) <==")
        for line in pod.status.log_tail:
            print(line)
    if getattr(args, "follow", False):
        return _follow_logs(cs, args, pods[0].status.log_tail)
    return 0


def _follow_logs(cs, args: argparse.Namespace, printed) -> int:
    """`kubectl logs -f` parity: poll the pod's bounded status.log_tail
    and print what's new. The tail is a rolling window, so new output is
    aligned by the largest overlap between the old tail's end and the
    new tail's start; a window that rotated entirely between polls
    prints whole (lines older than the window are gone by design)."""
    import time as _time

    from tfk8s_tpu.api.types import PodPhase
    from tfk8s_tpu.client.store import NotFound as _NotFound

    last = list(printed)
    deadline = (
        _time.time() + args.follow_timeout if args.follow_timeout else None
    )
    try:
        while deadline is None or _time.time() < deadline:
            _time.sleep(0.5)
            try:
                pod = cs.pods(args.namespace).get(args.name)
            except _NotFound:
                return 0  # pod deleted; stream over
            tail = pod.status.log_tail
            if tail != last:
                start = 0
                for k in range(min(len(last), len(tail)), 0, -1):
                    if last[-k:] == tail[:k]:
                        start = k
                        break
                for line in tail[start:]:
                    print(line, flush=True)
                last = list(tail)
            if pod.status.phase in (
                PodPhase.SUCCEEDED, PodPhase.FAILED, PodPhase.DRAINED
            ):
                return 0
    except KeyboardInterrupt:
        pass
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.command == "train":
        init_logging()
        return _cmd_train(args)
    if args.command == "apiserver":
        init_logging()
        return _cmd_apiserver(args)
    if args.command == "kubelet":
        init_logging()
        return _cmd_kubelet(args)
    if args.command in (
        "submit", "get", "describe", "delete", "logs", "scale", "apply",
        "suspend", "resume", "patch",
    ):
        init_logging()
        handler = {
            "submit": _cmd_submit,
            "patch": _cmd_patch,
            "get": _cmd_get,
            "describe": _cmd_describe,
            "delete": _cmd_delete,
            "logs": _cmd_logs,
            "scale": _cmd_scale,
            "apply": _cmd_apply,
            "suspend": _cmd_suspend,
            "resume": _cmd_resume,
        }[args.command]
        from tfk8s_tpu.client.store import StoreError

        try:
            return handler(args)
        except StoreError as exc:
            log.error("%s: %s", args.command, exc)
            return 1
        except (OSError, ValueError, KeyError) as exc:
            # missing kubeconfig/manifest file, malformed manifest,
            # unregistered kind — user errors, not stack traces
            log.error("%s: %s: %s", args.command, type(exc).__name__, exc)
            return 1
    opts = Options.from_args(args)
    init_logging(opts.log_level_int())
    if args.command == "operator":
        return _cmd_operator(opts)
    if args.command == "run":
        return _cmd_run(opts, args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
