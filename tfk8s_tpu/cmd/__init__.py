"""L5 CLI / entrypoint layer (SURVEY.md C1-C3): options, server, main."""

from tfk8s_tpu.cmd.main import main
from tfk8s_tpu.cmd.options import Options
from tfk8s_tpu.cmd.server import Server

__all__ = ["main", "Options", "Server"]
