"""Operator server — SURVEY.md C3 (`tf_operator/app/server.go`, 'Run
server' in images/tf2.png): wires clients → informers → controller,
gates reconciling behind leader election when asked (k8s-operator.md:59),
and runs until stopped.

The store backend is the in-process ClusterStore (client/store.py) — the
same List/Watch surface a real apiserver would present; swapping in a
networked backend changes only Clientset construction here (SURVEY.md §7
step 2).
"""

from __future__ import annotations

import http.server
import json
import threading
import urllib.parse
from typing import Optional

from tfk8s_tpu.client.clientset import Clientset, RESTConfig
from tfk8s_tpu.client.store import ClusterStore
from tfk8s_tpu.controller.leaderelection import LeaderElector
from tfk8s_tpu.cmd.options import Options
from tfk8s_tpu.obs.trace import get_tracer
from tfk8s_tpu.runtime.kubelet import LocalKubelet
from tfk8s_tpu.trainer.gang import SliceAllocator
from tfk8s_tpu.trainer.serve_controller import TPUServeController
from tfk8s_tpu.trainer.tpujob_controller import TPUJobController
from tfk8s_tpu.utils.logging import EventRecorder, Metrics, get_logger, init_logging

log = get_logger("server")


class Server:
    """Owns every long-lived component of one operator process."""

    def __init__(self, opts: Options, store: Optional[ClusterStore] = None):
        self.opts = opts
        # ALWAYS the process-default tracer: the kubelet and trainer
        # threads resolve get_tracer() themselves, so only the global
        # ring can hold the whole reconcile→pod→kubelet→trainer chain
        # /traces advertises. Isolation (tests) swaps the global via
        # obs.trace.set_tracer, never per-Server.
        self.tracer = get_tracer()
        self.metrics = Metrics()
        # span-drop pressure (tail sampling / ring eviction) surfaces on
        # this server's /metrics as tfk8s_trace_spans_dropped_total
        self.tracer.set_metrics(self.metrics)
        qps, burst = opts.qps, opts.burst
        if store is not None:
            self.store = store
        elif opts.kubeconfig:
            # remote backend: kubeconfig → RemoteStore, the reference's
            # BuildConfigFromFlags → NewForConfig path
            # (k8s-operator.md:92-102) — credentials (CA pin, bearer
            # token, client cert) ride along like rest.Config. The
            # kubeconfig's client limits take precedence — they describe
            # the server being talked to.
            from tfk8s_tpu.client.remote import load_kubeconfig, store_from_kubeconfig

            cfg = load_kubeconfig(opts.kubeconfig)
            self.store = store_from_kubeconfig(cfg)
            qps, burst = cfg.qps, cfg.burst
        else:
            # the in-process store exports its watch-coalescing counter
            # (tfk8s_watch_coalesced_total) on this server's /metrics
            self.store = ClusterStore(metrics=self.metrics)
        self.clientset = Clientset.new_for_config(
            self.store, RESTConfig(qps=qps, burst=burst)
        )
        self.allocator = SliceAllocator(opts.capacity or None)
        self.recorder = EventRecorder(sink=self.clientset)
        # image-input decode metrics (tfk8s_images_decoded_total /
        # decode-seconds / queue-depth) land on this registry: in the
        # single-process deployment (operator + local kubelet + trainer
        # threads, the hermetic `tfk8s run` path) the data plane's
        # counters surface on the SAME /metrics the controller serves
        from tfk8s_tpu.data.images import set_metrics as _images_set_metrics

        _images_set_metrics(self.metrics)
        self.controller = TPUJobController(
            self.clientset,
            allocator=self.allocator,
            recorder=self.recorder,
            metrics=self.metrics,
            resync_period=opts.resync_period_s,
            tracer=self.tracer,
        )
        # the serving control plane (TPUServe -> batched model-server
        # replicas) shares the clientset/recorder/registry — the serving
        # data plane's request metrics land on the same /metrics
        from tfk8s_tpu.runtime.server import set_metrics as _serve_set_metrics

        _serve_set_metrics(self.metrics)
        self.serve_controller = TPUServeController(
            self.clientset,
            recorder=self.recorder,
            metrics=self.metrics,
            resync_period=opts.resync_period_s,
            tracer=self.tracer,
        )
        self.kubelet = LocalKubelet(self.clientset) if opts.local_kubelet else None
        self._threads: list = []
        self._http: Optional[http.server.ThreadingHTTPServer] = None
        self.gateway = None  # started in run() when opts.gateway_port

    # -- observability endpoint (SURVEY.md §5: absent in the reference;
    #    /metrics Prometheus text, /healthz, /events JSON, /traces JSON) --

    def start_metrics_server(self, port: int) -> int:
        """Bind and serve on a daemon thread; returns the bound port
        (useful with port=0 in tests)."""
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                query = {
                    k: v[0]
                    for k, v in urllib.parse.parse_qs(parsed.query).items()
                }
                path = parsed.path
                if path == "/metrics":
                    body = server.metrics.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4"
                elif path == "/healthz":
                    body = b"ok"
                    ctype = "text/plain"
                elif path == "/events":
                    # ?key=<ns/name> and ?reason=<reason> filter
                    # server-side (EventRecorder.events already takes
                    # both; the handler forwards the query string)
                    body = json.dumps(
                        [
                            {
                                "ts": e.timestamp, "kind": e.kind, "key": e.key,
                                "reason": e.reason, "message": e.message,
                            }
                            for e in server.recorder.events(
                                key=query.get("key"),
                                reason=query.get("reason"),
                            )
                        ]
                    ).encode()
                    ctype = "application/json"
                elif path == "/debug/requests":
                    # zpages view of recently tail-sampled REQUEST traces
                    # (?trace_id= narrows; the gateway serves the same
                    # shape with its in-flight table populated)
                    from tfk8s_tpu.gateway.server import debug_requests

                    body = json.dumps(debug_requests(
                        server.tracer,
                        trace_id=query.get("trace_id"),
                        limit=int(query.get("limit", "32")),
                    )).encode()
                    ctype = "application/json"
                elif path == "/debug/decode":
                    # live slot/page occupancy per registered replica
                    from tfk8s_tpu.gateway.server import debug_decode

                    body = json.dumps(debug_decode()).encode()
                    ctype = "application/json"
                elif path == "/traces":
                    # one JSON object per trace, spans in start order;
                    # ?trace_id= narrows to one trace
                    want = query.get("trace_id")
                    body = json.dumps(
                        [
                            {
                                "trace_id": tid,
                                "spans": [s.to_dict() for s in spans],
                            }
                            for tid, spans in server.tracer.traces().items()
                            if want is None or tid == want
                        ]
                    ).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._http = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
        t = threading.Thread(target=self._http.serve_forever, daemon=True, name="metrics-http")
        t.start()
        self._threads.append(t)
        return self._http.server_address[1]

    def run(self, stop: threading.Event, block: bool = True) -> None:
        """Start kubelet + controller (possibly behind the leader gate).
        With ``block=False`` returns once everything is started."""
        init_logging(self.opts.log_level_int())
        if self.opts.metrics_port:
            port = self.start_metrics_server(self.opts.metrics_port)
            log.info("metrics endpoint on 127.0.0.1:%d", port)
        if self.opts.gateway_port:
            # the serving front door rides the leader-independent plane
            # (like the kubelet): it routes to whatever replicas exist,
            # regardless of which operator process reconciles them
            from tfk8s_tpu.gateway.server import GatewayServer

            self.gateway = GatewayServer(
                self.clientset,
                port=self.opts.gateway_port,
                metrics=self.metrics,
            )
            gw_port = self.gateway.serve_background()
            log.info("gateway front door on 127.0.0.1:%d", gw_port)
        if self.kubelet:
            self.kubelet.run(stop)  # informer-driven; returns immediately

        if not self.opts.leader_elect:
            log.info("starting controllers with %d workers", self.opts.workers)
            self.serve_controller.run(self.opts.workers, stop, block=False)
            self.controller.run(self.opts.workers, stop, block=block)
            if block:
                stop.wait()
            return

        elector = LeaderElector(
            self.clientset.generic("Lease", self.opts.namespace),
            identity=self.opts.identity,
            lease_name=self.opts.lease_name,
            namespace=self.opts.namespace,
            lease_duration_s=self.opts.lease_duration_s,
        )

        def lead(child_stop: threading.Event) -> None:
            log.info(
                "acquired lease %s as %s; starting controllers",
                self.opts.lease_name, self.opts.identity,
            )
            self.serve_controller.run(self.opts.workers, child_stop, block=False)
            self.controller.run(self.opts.workers, child_stop, block=False)

        def run_elector():
            elector.run(lead, stop, on_stopped_leading=self.shutdown)

        t = threading.Thread(target=run_elector, daemon=True, name="leader-elector")
        t.start()
        self._threads.append(t)
        self.elector = elector
        if block:
            stop.wait()

    def shutdown(self) -> None:
        if self._http is not None:
            self._http.shutdown()
        if self.gateway is not None:
            self.gateway.shutdown()
        self.controller.controller.shutdown()
        self.serve_controller.controller.shutdown()
