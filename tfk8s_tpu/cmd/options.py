"""Operator options/flags — SURVEY.md C2 (`tf_operator/app/options/
options.go`; 'Add flag' / 'Init flag and initlog' in images/tf2.png at
k8s-operator.md:57).

The reference's sequence is Main → New Option → Add flag → init
flag+log → Run server; this module is the Option half: a dataclass of
every operator knob plus argparse registration and parsing.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import socket
import uuid
from typing import Dict, Optional

from tfk8s_tpu.controller.controller import DEFAULT_SYNC_WORKERS


@dataclasses.dataclass
class Options:
    # controller
    workers: int = DEFAULT_SYNC_WORKERS
    resync_period_s: float = 0.0
    namespace: str = "default"
    # client rate limits (C10: token-bucket on the REST client)
    qps: float = 50.0
    burst: int = 100
    # leader election (C17)
    leader_elect: bool = False
    lease_name: str = "tfk8s-tpu-operator"
    lease_duration_s: float = 15.0
    identity: str = ""
    # cluster inventory: accelerator type -> number of slices
    capacity: Dict[str, int] = dataclasses.field(default_factory=dict)
    # run the in-process kubelet (hermetic/local backend)
    local_kubelet: bool = True
    # path to a kubeconfig JSON ({"server": "http://host:port", ...});
    # when set, the operator talks to that remote apiserver instead of an
    # in-process store (the reference's kubeconfig flag,
    # k8s-operator.md:206-207)
    kubeconfig: str = ""
    # observability endpoint (/metrics, /healthz, /events, /traces);
    # 0 = disabled
    metrics_port: int = 0
    # inference front door (POST /v1/serve/<ns>/<name>); 0 = disabled
    gateway_port: int = 0
    # logging
    log_level: str = "info"

    def __post_init__(self):
        if not self.identity:
            # pid + random suffix: unique across processes on one host
            # (two identical operator processes routinely get the same
            # object address, so id(self) would collide)
            self.identity = (
                f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:8]}"
            )

    @staticmethod
    def add_flags(parser: argparse.ArgumentParser) -> None:
        g = parser.add_argument_group("operator")
        g.add_argument("--workers", type=int, default=DEFAULT_SYNC_WORKERS,
                       help="reconcile worker count (Controller.Run N; "
                            "per-key in-flight exclusion makes raising "
                            "this safe)")
        g.add_argument("--resync-period", type=float, default=0.0, dest="resync_period_s",
                       help="informer resync period in seconds (0 = disabled)")
        g.add_argument("--namespace", default="default")
        g.add_argument("--qps", type=float, default=50.0,
                       help="client token-bucket refill rate")
        g.add_argument("--burst", type=int, default=100,
                       help="client token-bucket burst size")
        g.add_argument("--leader-elect", action="store_true", dest="leader_elect",
                       help="gate reconciling behind a lease (HA)")
        g.add_argument("--lease-name", default="tfk8s-tpu-operator")
        g.add_argument("--lease-duration", type=float, default=15.0,
                       dest="lease_duration_s")
        g.add_argument("--identity", default="",
                       help="leader-election identity (default: hostname-derived)")
        g.add_argument("--capacity", default="{}",
                       help='slice inventory as JSON, e.g. \'{"v5p-32": 4}\'')
        g.add_argument("--no-local-kubelet", action="store_false",
                       dest="local_kubelet",
                       help="do not run the in-process pod executor")
        g.add_argument("--kubeconfig", default="",
                       help="kubeconfig JSON path; talk to a remote "
                       "apiserver instead of the in-process store")
        g.add_argument("--metrics-port", type=int, default=0, dest="metrics_port",
                       help="serve /metrics, /healthz, /events, /traces "
                            "on this port (0=off)")
        g.add_argument("--gateway-port", type=int, default=0, dest="gateway_port",
                       help="serve the inference front door (POST "
                            "/v1/serve/<ns>/<name>) on this port (0=off)")
        g.add_argument("--log-level", default="info",
                       choices=["debug", "info", "warning", "error"])

    @classmethod
    def from_args(cls, args: argparse.Namespace) -> "Options":
        capacity = args.capacity
        if isinstance(capacity, str):
            capacity = json.loads(capacity or "{}")
        return cls(
            workers=args.workers,
            resync_period_s=args.resync_period_s,
            namespace=args.namespace,
            qps=args.qps,
            burst=args.burst,
            leader_elect=args.leader_elect,
            lease_name=args.lease_name,
            lease_duration_s=args.lease_duration_s,
            identity=args.identity,
            capacity=capacity,
            local_kubelet=args.local_kubelet,
            kubeconfig=getattr(args, "kubeconfig", ""),
            metrics_port=args.metrics_port,
            gateway_port=getattr(args, "gateway_port", 0),
            log_level=args.log_level,
        )

    def log_level_int(self) -> int:
        return getattr(logging, self.log_level.upper(), logging.INFO)
