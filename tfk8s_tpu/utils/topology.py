"""TPU slice topology math.

The reference is topology-agnostic — its scaling axis is replica count
(k8s-operator.md:6) and a GPU pod is a fungible resource. On TPU the unit of
scheduling is a *slice*: an ICI-connected grid of chips carved from a pod,
requested by accelerator type (``v5p-32``) and optionally an explicit chip
grid (``2x2x4``). Gang admission, mesh construction, and placement all hang
off this module (SURVEY.md §7 hard part 1).

Naming conventions follow Cloud TPU:

- ``v4-N`` / ``v5p-N``: N counts *TensorCores*, 2 per chip -> N/2 chips,
  4 chips per host, 3-D ICI torus.
- ``v5litepod-N`` / ``v6e-N``: N counts chips, 2-D ICI grid; single host up
  to 8 chips, 4 chips per host beyond.
- ``cpu-N`` (hermetic tests / local backend): N virtual devices, one host,
  no ICI — stands in for a slice the way the reference's fake clientset
  stands in for an apiserver (SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import List, Tuple

_GEN_RE = re.compile(r"^(v[0-9]+[a-z]*|cpu|v5litepod)(?:-([0-9]+))?$")

# Cloud TPU generation -> GKE nodepool accelerator label value
# (cloud.google.com/gke-tpu-accelerator on real TPU nodepools). Absent
# generations (v2/v3/cpu) have no GKE TPU nodepool shape — validation
# rejects provider="gke" for them rather than rendering a half-GKE pod.
GKE_ACCELERATOR = {
    "v4": "tpu-v4-podslice",
    "v5p": "tpu-v5p-slice",
    "v5litepod": "tpu-v5-lite-podslice",
    "v5e": "tpu-v5-lite-podslice",
    "v6e": "tpu-v6e-slice",
}

# generation -> (counts_cores, cores_per_chip, chips_per_host, ici_dims)
_GENERATIONS = {
    "v2": (True, 2, 4, 2),
    "v3": (True, 2, 4, 2),
    "v4": (True, 2, 4, 3),
    "v5p": (True, 2, 4, 3),
    "v5litepod": (False, 1, 4, 2),
    "v5e": (False, 1, 4, 2),
    "v6e": (False, 1, 4, 2),
    "cpu": (False, 1, None, 1),  # all devices on one host
}


@dataclasses.dataclass(frozen=True)
class SliceInfo:
    """Resolved shape of one slice of an accelerator type."""

    accelerator: str
    generation: str
    chips: int
    cores_per_chip: int
    hosts: int
    topology: Tuple[int, ...]  # chip grid, e.g. (2, 2, 4)

    @property
    def chips_per_host(self) -> int:
        return self.chips // self.hosts

    @property
    def cores(self) -> int:
        return self.chips * self.cores_per_chip


class TopologyError(ValueError):
    pass


def parse_topology(s: str) -> Tuple[int, ...]:
    """``"2x2x4"`` -> ``(2, 2, 4)``."""
    try:
        dims = tuple(int(p) for p in s.lower().split("x"))
    except ValueError:
        raise TopologyError(f"malformed topology {s!r}")
    if not dims or any(d < 1 for d in dims):
        raise TopologyError(f"malformed topology {s!r}")
    return dims


def default_topology(chips: int, ndims: int) -> Tuple[int, ...]:
    """Near-cubic factorization of ``chips`` into an ``ndims``-D grid,
    preferring balanced dims (an ICI torus wants compact shapes)."""
    if ndims <= 1:
        return (chips,)
    dims = [1] * ndims
    # Peel off prime factors largest-first onto the currently-smallest dim.
    for p in _prime_factors(chips):
        dims[dims.index(min(dims))] *= p
    return tuple(sorted(dims))


def _prime_factors(n: int) -> List[int]:
    out = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return sorted(out, reverse=True)


# -- host-grid geometry (ICI-topology-aware placement) -----------------------
#
# A slice's chip grid partitions into per-host blocks: each host owns an
# ICI-contiguous sub-block (Cloud TPU reality: a v5p host is a 2x2x1
# chunk of the chip torus). The gang allocator places jobs as
# axis-aligned BOXES of host blocks, so every admitted gang is
# ICI-contiguous by construction (north star: "gang scheduling and
# placement become ICI-topology aware"; SURVEY.md §7 hard part 1).


def host_block_shape(info: SliceInfo) -> Tuple[int, ...]:
    """Per-host chip sub-block: chips_per_host factored across the
    topology dims as BALANCED as divisibility allows — each prime factor
    lands on the smallest block dim that can still grow. This reproduces
    the real machine geometry (a v4/v5p host owns a 2x2x1 chunk of the
    chip torus, so v5p-128's (4,4,4) grid tiles into 2x2x1 host blocks —
    NOT the (4,1,1) a greedy per-dim gcd would produce)."""
    block = [1] * len(info.topology)
    for p in sorted(_prime_factors(info.chips_per_host)):
        candidates = [
            i
            for i, dim in enumerate(info.topology)
            if dim % (block[i] * p) == 0
        ]
        if not candidates:
            raise TopologyError(
                f"{info.accelerator}: cannot tile {info.chips_per_host} "
                f"chips/host into topology {info.topology}"
            )
        i = min(candidates, key=lambda i: block[i])
        block[i] *= p
    return tuple(block)


def host_grid_shape(info: SliceInfo) -> Tuple[int, ...]:
    """How the slice's hosts arrange as a grid of host blocks."""
    block = host_block_shape(info)
    return tuple(t // b for t, b in zip(info.topology, block))


def host_coords(info: SliceInfo, host_index: int) -> Tuple[int, ...]:
    """Host index -> coordinates in the host grid (C-order: last dim
    fastest, so consecutive indices are grid-adjacent)."""
    grid = host_grid_shape(info)
    if not 0 <= host_index < info.hosts:
        raise TopologyError(f"host {host_index} out of range for {info.accelerator}")
    coords = []
    rem = host_index
    for dim in reversed(grid):
        coords.append(rem % dim)
        rem //= dim
    return tuple(reversed(coords))


def host_index_of(info: SliceInfo, coords: Tuple[int, ...]) -> int:
    grid = host_grid_shape(info)
    idx = 0
    for c, dim in zip(coords, grid):
        idx = idx * dim + c
    return idx


def hosts_contiguous(info: SliceInfo, host_indices) -> bool:
    """True iff the hosts tile an axis-aligned box of the host grid —
    the ICI-contiguity property the allocator guarantees."""
    coords = [host_coords(info, h) for h in host_indices]
    if not coords:
        return False
    lo = tuple(min(c[d] for c in coords) for d in range(len(coords[0])))
    hi = tuple(max(c[d] for c in coords) for d in range(len(coords[0])))
    vol = math.prod(h - l + 1 for l, h in zip(lo, hi))
    return vol == len(set(coords)) == len(coords)


def parse_accelerator(accelerator: str, topology: str = "") -> SliceInfo:
    """Resolve an accelerator type string (+ optional explicit topology) into
    a :class:`SliceInfo`. Raises :class:`TopologyError` on malformed or
    inconsistent requests — surfaced to users via api/validation.py."""
    acc = accelerator.strip().lower()
    m = _GEN_RE.match(acc)
    if not m:
        raise TopologyError(f"unknown accelerator type {accelerator!r}")
    gen, size = m.group(1), m.group(2)
    if gen not in _GENERATIONS:
        raise TopologyError(f"unknown accelerator generation {gen!r}")
    counts_cores, cores_per_chip, chips_per_host, ndims = _GENERATIONS[gen]

    n = int(size) if size else 1
    if n < 1:
        raise TopologyError(f"accelerator size must be >= 1, got {accelerator!r}")
    if counts_cores:
        if n % cores_per_chip:
            raise TopologyError(
                f"{gen} sizes count TensorCores ({cores_per_chip}/chip); "
                f"{n} is not a multiple of {cores_per_chip}"
            )
        chips = n // cores_per_chip
    else:
        chips = n

    if topology:
        topo = parse_topology(topology)
        if math.prod(topo) != chips:
            raise TopologyError(
                f"topology {topology!r} has {math.prod(topo)} chips but "
                f"{accelerator!r} has {chips}"
            )
        if gen != "cpu" and len(topo) != ndims:
            raise TopologyError(
                f"{gen} slices have a {ndims}-D ICI grid; topology "
                f"{topology!r} is {len(topo)}-D"
            )
    else:
        topo = default_topology(chips, ndims)

    if chips_per_host is None or chips <= (8 if gen in ("v5litepod", "v5e", "v6e") else chips_per_host):
        hosts = 1
    else:
        if chips % chips_per_host:
            raise TopologyError(
                f"{accelerator!r}: {chips} chips not divisible into "
                f"{chips_per_host}-chip hosts"
            )
        hosts = chips // chips_per_host

    return SliceInfo(
        accelerator=acc,
        generation=gen,
        chips=chips,
        cores_per_chip=cores_per_chip,
        hosts=hosts,
        topology=topo,
    )
