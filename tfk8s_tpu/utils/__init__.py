"""Shared utilities: topology math, structured logging, clocks."""
