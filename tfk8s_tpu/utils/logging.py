"""Structured logging for the control plane.

The reference initializes logging at startup ("initlog", images/tf2.png at
k8s-operator.md:57) and error-logs via glog (images/tf4.PNG). Here: stdlib
logging with one configuration point, plus a structured event recorder the
controller uses for observability (SURVEY.md §5 'Metrics / logging').
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_FORMAT = "%(asctime)s %(levelname).1s %(name)s] %(message)s"
_configured = False


def init_logging(level: int = logging.INFO) -> None:
    """The 'initlog' step of startup (images/tf2.png)."""
    global _configured
    if not _configured:
        logging.basicConfig(level=level, format=_FORMAT)
        _configured = True


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"tfk8s.{name}")


@dataclasses.dataclass
class Event:
    """One structured control-plane event (job created, gang admitted,
    pod failed, ...)."""

    timestamp: float
    kind: str
    key: str  # namespace/name of the involved object
    reason: str
    message: str = ""


class EventRecorder:
    """Append-only in-memory event log; tests and the CLI 'describe' read
    it. With a ``sink`` clientset, every event is ALSO mirrored into the
    cluster as a core/v1-style Event object (api/types.py Event),
    k8s-aggregated — one object per (involved object, reason) with a
    bumped count — so clients read event history through the apiserver
    instead of the operator process. Best-effort: sink failures never
    break the reconcile path that emitted the event."""

    def __init__(self, capacity: int = 4096, sink=None):
        self._lock = threading.Lock()
        self._events: List[Event] = []
        self._capacity = capacity
        self._sink = sink
        self._queue = None
        if sink is not None:
            # mirror ASYNCHRONOUSLY (k8s records events via a broadcaster
            # for the same reason): the sink does REST round-trips through
            # the operator's rate-limited client, and reconcile workers
            # must never stall behind event bookkeeping
            import queue

            self._queue = queue.Queue(maxsize=4096)
            threading.Thread(
                target=self._mirror_loop, name="event-mirror", daemon=True
            ).start()

    def event(self, kind: str, key: str, reason: str, message: str = "") -> None:
        ev = Event(time.time(), kind, key, reason, message)
        with self._lock:
            self._events.append(ev)
            if len(self._events) > self._capacity:
                self._events = self._events[-self._capacity :]
        get_logger("events").info("%s %s %s %s", kind, key, reason, message)
        if self._queue is not None:
            try:
                self._queue.put_nowait(ev)
            except Exception:  # noqa: BLE001 — full queue: drop, best-effort
                pass

    def _mirror_loop(self) -> None:
        while True:
            ev = self._queue.get()
            try:
                self._mirror(ev)
            except Exception as e:  # noqa: BLE001 — events are best-effort
                get_logger("events").debug("event sink failed: %s", e)
            finally:
                self._queue.task_done()

    def flush(self) -> None:
        """Block until every queued event has been mirrored (tests)."""
        if self._queue is not None:
            self._queue.join()

    def _mirror(self, ev: Event) -> None:
        from tfk8s_tpu.api import types as t
        from tfk8s_tpu.client.store import AlreadyExists, Conflict, NotFound

        ns, _, obj_name = ev.key.partition("/")
        ns = ns or "default"
        # deterministic per (kind, object, reason): repeats aggregate. The
        # kind is part of the identity — a Pod and a TPUJob sharing a name
        # in one namespace must not merge into one Event.
        name = f"{ev.kind.lower()}.{obj_name}.{ev.reason.lower()}"
        client = self._sink.generic("Event", ns)
        for _ in range(3):
            try:
                existing = client.get(name)
            except NotFound:
                try:
                    client.create(
                        t.Event(
                            metadata=t.ObjectMeta(name=name, namespace=ns),
                            involved_kind=ev.kind,
                            involved_key=ev.key,
                            reason=ev.reason,
                            message=ev.message,
                            count=1,
                            first_timestamp=ev.timestamp,
                            last_timestamp=ev.timestamp,
                        )
                    )
                    return
                except AlreadyExists:
                    continue
            existing.count += 1
            existing.last_timestamp = ev.timestamp
            existing.message = ev.message or existing.message
            try:
                client.update(existing)
                return
            except (Conflict, NotFound):
                continue

    def events(self, key: Optional[str] = None, reason: Optional[str] = None) -> List[Event]:
        with self._lock:
            return [
                e
                for e in self._events
                if (key is None or e.key == key) and (reason is None or e.reason == reason)
            ]


_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)


def _series_key(name: str, labels: Optional[Dict[str, str]]):
    """Series identity: (name, sorted label items) — one series per unique
    label set, the Prometheus data model."""
    if not labels:
        return (name, ())
    return (name, tuple(sorted((k, str(v)) for k, v in labels.items())))


def _escape_label_value(v: str) -> str:
    """Prometheus exposition escaping for label values: backslash, double
    quote, and newline (the three characters the format reserves)."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _sanitize_name(n: str) -> str:
    return n.replace(".", "_").replace("-", "_")


def _render_labels(lk, extra: str = "") -> str:
    """``(("job","a"),("ns","d"))`` -> ``{job="a",ns="d"}`` (values
    escaped); ``extra`` appends a pre-rendered pair (the histogram
    ``le``)."""
    pairs = ['{}="{}"'.format(k, _escape_label_value(v)) for k, v in lk]
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


class Metrics:
    """Counter/gauge/histogram registry with labeled series and Prometheus
    text exposition (SURVEY.md §5: 'no metrics endpoint evidenced' in the
    reference — this is the build's addition).

    Series identity is ``(name, labels)``: ``inc("pods_created_total",
    labels={"namespace": ns})`` and the same name with different labels
    are independent series, exposed as ``name{k="v",...} value`` with
    label values escaped per the exposition format. Per-object series
    (per-job training gauges) carry their owner as labels so deletion can
    GC them precisely with :meth:`remove_labels` — no name-prefix
    matching, no way to take out a neighbor's series by accident."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Any, float] = {}
        self._gauges: Dict[Any, float] = {}
        # series key -> [bucket counts..., +inf count], plus _sum
        self._hist_counts: Dict[Any, List[float]] = {}
        self._hist_sum: Dict[Any, float] = {}
        # (series key, bucket index) -> (trace_id, observed value): the
        # most recent exemplar per bucket, linking the bucket to a kept
        # trace in the exposition (OpenMetrics-style " # {...}" suffix)
        self._hist_exemplars: Dict[Any, Dict[int, Tuple[str, float]]] = {}
        self._help: Dict[str, str] = {}

    # -- write side --------------------------------------------------------

    def describe(self, name: str, help_text: str) -> None:
        """Register a ``# HELP`` line for ``name`` (optional; exposition
        emits it ahead of the family's ``# TYPE`` line when present)."""
        with self._lock:
            self._help[name] = help_text

    def inc(
        self, name: str, value: float = 1.0,
        labels: Optional[Dict[str, str]] = None,
    ) -> None:
        key = _series_key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(
        self, name: str, value: float,
        labels: Optional[Dict[str, str]] = None,
    ) -> None:
        with self._lock:
            self._gauges[_series_key(name, labels)] = value

    def observe(
        self, name: str, value: float,
        labels: Optional[Dict[str, str]] = None,
        exemplar: Optional[str] = None,
    ) -> None:
        """Record one histogram observation (e.g. a sync latency).
        ``exemplar`` is a trace id to pin on the bucket the observation
        lands in — exposition renders it as the OpenMetrics
        ``# {trace_id="..."} value`` suffix so a slow bucket links to a
        kept trace. Last writer per bucket wins."""
        key = _series_key(name, labels)
        with self._lock:
            counts = self._hist_counts.setdefault(
                key, [0.0] * (len(_DEFAULT_BUCKETS) + 1)
            )
            for i, ub in enumerate(_DEFAULT_BUCKETS):
                if value <= ub:
                    counts[i] += 1
                    bucket = i
                    break
            else:
                counts[-1] += 1
                bucket = len(_DEFAULT_BUCKETS)
            self._hist_sum[key] = self._hist_sum.get(key, 0.0) + value
            if exemplar:
                self._hist_exemplars.setdefault(key, {})[bucket] = (
                    exemplar, value,
                )

    # -- read side ---------------------------------------------------------

    def get_counter(
        self, name: str, labels: Optional[Dict[str, str]] = None
    ) -> Optional[float]:
        with self._lock:
            return self._counters.get(_series_key(name, labels))

    def get_gauge(
        self, name: str, labels: Optional[Dict[str, str]] = None
    ) -> Optional[float]:
        with self._lock:
            return self._gauges.get(_series_key(name, labels))

    def remove_labels(self, match: Dict[str, str]) -> int:
        """Label-based GC: drop every series (any name) whose label set
        contains ALL of ``match``'s pairs — per-job series must die with
        their job or a long-lived operator leaks memory and scrapes stale
        values. Returns the number of series removed."""
        want = set((k, str(v)) for k, v in match.items())
        removed = 0
        with self._lock:
            for table in (
                self._counters, self._gauges, self._hist_counts,
                self._hist_sum, self._hist_exemplars,
            ):
                doomed = [k for k in table if want.issubset(set(k[1]))]
                for k in doomed:
                    del table[k]
                # _hist_sum/_hist_exemplars share keys with _hist_counts;
                # one series each (identity, not ==: two empty tables
                # compare equal and would double-count)
                if any(
                    table is t for t in
                    (self._counters, self._gauges, self._hist_counts)
                ):
                    removed += len(doomed)
        return removed

    @staticmethod
    def _flat(key) -> str:
        name, lk = key
        return name + _render_labels(lk)

    def snapshot(self) -> Dict[str, Any]:
        """Flattened view for tests/CLI: unlabeled series keep their plain
        name; labeled ones render as ``name{k="v",...}``."""
        with self._lock:
            hists = {}
            for key, counts in self._hist_counts.items():
                hists[self._flat(key)] = {
                    "count": sum(counts),
                    "sum": self._hist_sum.get(key, 0.0),
                }
            return {
                "counters": {self._flat(k): v for k, v in self._counters.items()},
                "gauges": {self._flat(k): v for k, v in self._gauges.items()},
                "histograms": hists,
            }

    def prometheus_text(self) -> str:
        """Prometheus exposition format: names sanitized (dots/dashes ->
        underscores), one ``# HELP``/``# TYPE`` header per metric family,
        label values escaped."""
        with self._lock:
            lines: List[str] = []
            seen: set = set()

            def header(raw_name: str, sname: str, kind: str) -> None:
                if sname in seen:
                    return
                seen.add(sname)
                help_text = self._help.get(raw_name)
                if help_text:
                    lines.append(f"# HELP {sname} {help_text}")
                lines.append(f"# TYPE {sname} {kind}")

            for (name, lk), v in sorted(
                self._counters.items(), key=lambda kv: kv[0]
            ):
                n = _sanitize_name(name)
                header(name, n, "counter")
                lines.append(f"{n}{_render_labels(lk)} {v}")
            for (name, lk), v in sorted(
                self._gauges.items(), key=lambda kv: kv[0]
            ):
                n = _sanitize_name(name)
                header(name, n, "gauge")
                lines.append(f"{n}{_render_labels(lk)} {v}")
            for (name, lk), counts in sorted(
                self._hist_counts.items(), key=lambda kv: kv[0]
            ):
                n = _sanitize_name(name)
                header(name, n, "histogram")
                exemplars = self._hist_exemplars.get((name, lk), {})

                def _ex(bucket: int) -> str:
                    ex = exemplars.get(bucket)
                    if ex is None:
                        return ""
                    tid, val = ex
                    return f' # {{trace_id="{_escape_label_value(tid)}"}} {val}'

                cum = 0.0
                for i, ub in enumerate(_DEFAULT_BUCKETS):
                    cum += counts[i]
                    le = 'le="{}"'.format(ub)
                    lines.append(
                        f"{n}_bucket{_render_labels(lk, le)} {cum}{_ex(i)}"
                    )
                cum += counts[-1]
                inf = 'le="+Inf"'
                lines.append(
                    f"{n}_bucket{_render_labels(lk, inf)} {cum}"
                    f"{_ex(len(_DEFAULT_BUCKETS))}"
                )
                lines.append(
                    f"{n}_sum{_render_labels(lk)} {self._hist_sum.get((name, lk), 0.0)}"
                )
                lines.append(f"{n}_count{_render_labels(lk)} {cum}")
            return "\n".join(lines) + "\n"
