"""Structured logging for the control plane.

The reference initializes logging at startup ("initlog", images/tf2.png at
k8s-operator.md:57) and error-logs via glog (images/tf4.PNG). Here: stdlib
logging with one configuration point, plus a structured event recorder the
controller uses for observability (SURVEY.md §5 'Metrics / logging').
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Dict, List, Optional

_FORMAT = "%(asctime)s %(levelname).1s %(name)s] %(message)s"
_configured = False


def init_logging(level: int = logging.INFO) -> None:
    """The 'initlog' step of startup (images/tf2.png)."""
    global _configured
    if not _configured:
        logging.basicConfig(level=level, format=_FORMAT)
        _configured = True


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"tfk8s.{name}")


@dataclasses.dataclass
class Event:
    """One structured control-plane event (job created, gang admitted,
    pod failed, ...)."""

    timestamp: float
    kind: str
    key: str  # namespace/name of the involved object
    reason: str
    message: str = ""


class EventRecorder:
    """Append-only in-memory event log; tests and the CLI 'describe' read
    it. With a ``sink`` clientset, every event is ALSO mirrored into the
    cluster as a core/v1-style Event object (api/types.py Event),
    k8s-aggregated — one object per (involved object, reason) with a
    bumped count — so clients read event history through the apiserver
    instead of the operator process. Best-effort: sink failures never
    break the reconcile path that emitted the event."""

    def __init__(self, capacity: int = 4096, sink=None):
        self._lock = threading.Lock()
        self._events: List[Event] = []
        self._capacity = capacity
        self._sink = sink
        self._queue = None
        if sink is not None:
            # mirror ASYNCHRONOUSLY (k8s records events via a broadcaster
            # for the same reason): the sink does REST round-trips through
            # the operator's rate-limited client, and reconcile workers
            # must never stall behind event bookkeeping
            import queue

            self._queue = queue.Queue(maxsize=4096)
            threading.Thread(
                target=self._mirror_loop, name="event-mirror", daemon=True
            ).start()

    def event(self, kind: str, key: str, reason: str, message: str = "") -> None:
        ev = Event(time.time(), kind, key, reason, message)
        with self._lock:
            self._events.append(ev)
            if len(self._events) > self._capacity:
                self._events = self._events[-self._capacity :]
        get_logger("events").info("%s %s %s %s", kind, key, reason, message)
        if self._queue is not None:
            try:
                self._queue.put_nowait(ev)
            except Exception:  # noqa: BLE001 — full queue: drop, best-effort
                pass

    def _mirror_loop(self) -> None:
        while True:
            ev = self._queue.get()
            try:
                self._mirror(ev)
            except Exception as e:  # noqa: BLE001 — events are best-effort
                get_logger("events").debug("event sink failed: %s", e)
            finally:
                self._queue.task_done()

    def flush(self) -> None:
        """Block until every queued event has been mirrored (tests)."""
        if self._queue is not None:
            self._queue.join()

    def _mirror(self, ev: Event) -> None:
        from tfk8s_tpu.api import types as t
        from tfk8s_tpu.client.store import AlreadyExists, Conflict, NotFound

        ns, _, obj_name = ev.key.partition("/")
        ns = ns or "default"
        # deterministic per (kind, object, reason): repeats aggregate. The
        # kind is part of the identity — a Pod and a TPUJob sharing a name
        # in one namespace must not merge into one Event.
        name = f"{ev.kind.lower()}.{obj_name}.{ev.reason.lower()}"
        client = self._sink.generic("Event", ns)
        for _ in range(3):
            try:
                existing = client.get(name)
            except NotFound:
                try:
                    client.create(
                        t.Event(
                            metadata=t.ObjectMeta(name=name, namespace=ns),
                            involved_kind=ev.kind,
                            involved_key=ev.key,
                            reason=ev.reason,
                            message=ev.message,
                            count=1,
                            first_timestamp=ev.timestamp,
                            last_timestamp=ev.timestamp,
                        )
                    )
                    return
                except AlreadyExists:
                    continue
            existing.count += 1
            existing.last_timestamp = ev.timestamp
            existing.message = ev.message or existing.message
            try:
                client.update(existing)
                return
            except (Conflict, NotFound):
                continue

    def events(self, key: Optional[str] = None, reason: Optional[str] = None) -> List[Event]:
        with self._lock:
            return [
                e
                for e in self._events
                if (key is None or e.key == key) and (reason is None or e.reason == reason)
            ]


_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)


class Metrics:
    """Counter/gauge/histogram registry with Prometheus text exposition
    (SURVEY.md §5: 'no metrics endpoint evidenced' in the reference —
    this is the build's addition)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        # name -> [bucket counts..., +inf count], plus _sum/_count
        self.hist_counts: Dict[str, List[float]] = {}
        self.hist_sum: Dict[str, float] = {}

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0.0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def remove_prefix(self, prefix: str) -> None:
        """Drop every series whose name starts with ``prefix`` — per-job
        series (tpujob.training.<ns>.<job>.*) must die with their job or
        a long-lived operator leaks memory and scrapes stale values."""
        with self._lock:
            for table in (self.counters, self.gauges, self.hist_counts, self.hist_sum):
                for name in [n for n in table if n.startswith(prefix)]:
                    del table[name]

    def observe(self, name: str, value: float) -> None:
        """Record one histogram observation (e.g. a sync latency)."""
        with self._lock:
            counts = self.hist_counts.setdefault(
                name, [0.0] * (len(_DEFAULT_BUCKETS) + 1)
            )
            for i, ub in enumerate(_DEFAULT_BUCKETS):
                if value <= ub:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self.hist_sum[name] = self.hist_sum.get(name, 0.0) + value

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            hists = {}
            for name, counts in self.hist_counts.items():
                hists[name] = {
                    "count": sum(counts),
                    "sum": self.hist_sum.get(name, 0.0),
                }
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "histograms": hists,
            }

    def prometheus_text(self) -> str:
        """Prometheus exposition format; metric names sanitized
        (dots -> underscores)."""
        def san(n: str) -> str:
            return n.replace(".", "_").replace("-", "_")

        with self._lock:
            lines: List[str] = []
            for name, v in sorted(self.counters.items()):
                lines.append(f"# TYPE {san(name)} counter")
                lines.append(f"{san(name)} {v}")
            for name, v in sorted(self.gauges.items()):
                lines.append(f"# TYPE {san(name)} gauge")
                lines.append(f"{san(name)} {v}")
            for name, counts in sorted(self.hist_counts.items()):
                n = san(name)
                lines.append(f"# TYPE {n} histogram")
                cum = 0.0
                for i, ub in enumerate(_DEFAULT_BUCKETS):
                    cum += counts[i]
                    lines.append(f'{n}_bucket{{le="{ub}"}} {cum}')
                cum += counts[-1]
                lines.append(f'{n}_bucket{{le="+Inf"}} {cum}')
                lines.append(f"{n}_sum {self.hist_sum.get(name, 0.0)}")
                lines.append(f"{n}_count {cum}")
            return "\n".join(lines) + "\n"
