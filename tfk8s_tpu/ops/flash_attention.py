"""Flash attention as Pallas TPU kernels — forward AND backward.

Attention is the one op in the transformer stack where the XLA default
materializes an [L, L] score matrix in HBM; the flash formulation never
does — each (batch*head, q-block) program streams K/V blocks through
VMEM, maintaining the online-softmax running max/denominator, so HBM
traffic is O(L·d) and the MXU sees back-to-back [BQ,d]x[d,BK] and
[BQ,BK]x[BK,d] matmuls (pallas_guide: MXU/VMEM model, grid/BlockSpec).

The backward is the FlashAttention-2 recomputation scheme, also as
Pallas kernels: the forward saves only the per-row logsumexp (O(L), not
O(L²)); the backward recomputes probabilities blockwise from (q, k,
lse) and accumulates
    dv += pᵀ·do,   ds = p∘(do·vᵀ − D),   dk += dsᵀ·q,   dq += ds·k
with D = rowsum(do∘o) computed in-kernel from the o/do blocks already
in VMEM. Two kernels: one gridded over q blocks (dq), one over k blocks
(dk, dv) — each accumulator lives in exactly one program, so no
cross-program reduction races. Training (the measured workload) therefore runs flash end to end.

Causal masking is bottom-right aligned (matches ``_reference``'s tril
with k=lk-lq); blocks entirely above the diagonal are skipped in all
three kernels. Off-TPU the kernels run in interpreter mode, which is how
the hermetic CPU tests cover them.

Layout [b, l, h, d] matches models/transformer.py; q must arrive
pre-scaled (by 1/sqrt(d)), exactly like ``dot_product_attention``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30

# flash becomes the default attention above this sequence length on TPU
# (models/bert.py task_for_mesh): below it the XLA fused attention is
# already fast and compile time dominates; above it the [L, L] scores
# buffer starts to hurt HBM (and eventually OOMs).
FLASH_SEQ_THRESHOLD = 1024

# Default q/k block sizes; explicit attention_impl="flash" configs may
# pass their own. Auto-selection picks the largest candidates that
# divide the sequence (pick_blocks), so any 128-multiple length
# qualifies — not just DEFAULT_BLOCK_Q multiples (VERDICT r2 next #4).
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 256
_BLOCK_Q_CANDIDATES = (512, 256, 128)
_BLOCK_K_CANDIDATES = (256, 128)


def pick_blocks(seq_len: int):
    """Largest (block_q, block_k) candidates dividing ``seq_len``, or
    None when no candidate divides it (seq not a 128 multiple)."""
    bq = next((b for b in _BLOCK_Q_CANDIDATES if seq_len % b == 0), None)
    bk = next((b for b in _BLOCK_K_CANDIDATES if seq_len % b == 0), None)
    if bq is None or bk is None:
        return None
    return bq, bk


def autotune_blocks(
    seq_len: int,
    batch: int = 8,
    heads: int = 12,
    head_dim: int = 64,
    candidates=None,
    iters: int = 4,
    causal: bool = True,
):
    """Time fwd+bwd for each (block_q, block_k) candidate at the given
    geometry on the CURRENT backend and return (block_q, block_k, ms).
    Meant for bench/build time (each candidate costs a compile); runtime
    callers use pick_blocks' static choice."""
    import time as _time

    import numpy as np

    if candidates is None:
        # (2048, *) blocks exceed the v5e scoped-VMEM limit in the bwd
        # kernel (measured: 19.95M vs the 16M cap) — keep them out
        candidates = [
            (512, 256), (512, 512), (256, 256), (1024, 512), (1024, 1024),
        ]
    candidates = [
        (bq, bk) for bq, bk in candidates
        if seq_len % bq == 0 and seq_len % bk == 0
    ]
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(
        rng.standard_normal((batch, seq_len, heads, head_dim)), jnp.bfloat16
    )
    q, k, v = mk(), mk(), mk()
    best = None
    for bq, bk in candidates:
        grad = jax.grad(
            lambda q, k, v: jnp.sum(
                flash_attention(
                    q, k, v, causal=causal, block_q=bq, block_k=bk
                ).astype(jnp.float32) ** 2
            ),
            argnums=(0, 1, 2),
        )

        # k/v enter as jit ARGUMENTS (the scan body closes over their
        # TRACED values, which become loop-invariant captures): closing
        # over the device arrays themselves would bake tens of MB of
        # constants into each candidate's HLO — the round-1
        # remote-compile 413 failure mode (bench.py docstring).
        def _run(q, k, v):
            def body(c, _):
                dq, dk, dv = grad(c, k, v)
                return c + 0.0 * (dq + dk + dv).astype(c.dtype), ()

            return jax.lax.scan(body, q, None, length=iters)[0]

        run = jax.jit(_run)
        try:
            out = run(q, k, v)
            float(jnp.sum(out.astype(jnp.float32)))  # compile + warm
        except Exception:  # noqa: BLE001 — e.g. VMEM overflow at this block
            continue
        times = []
        for _ in range(3):
            t0 = _time.perf_counter()
            out = run(q, k, v)
            float(jnp.sum(out.astype(jnp.float32)))
            times.append(_time.perf_counter() - t0)
        ms = sorted(times)[1] / iters * 1000
        if best is None or ms < best[2]:
            best = (bq, bk, ms)
    return best

# Mosaic requires the last two dims of every block to be (8k, 128k) or
# equal to the array dims, so the per-row logsumexp is stored broadcast
# across a 128-lane minor dim (same layout as the stock jax TPU flash
# kernel's l/m residuals) — the physical HBM tile is 128 lanes wide for
# a 1-wide array anyway, so this costs nothing extra.
_LSE_LANES = 128


# -- forward -----------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, *rest, block_k: int, causal: bool, has_mask: bool = False
):
    # positional refs: [mask,] o [, lse] — mask is an optional INPUT so it
    # precedes the outputs in pallas_call's ref order
    if has_mask:
        mask_ref, *outs = rest
    else:
        mask_ref, outs = None, list(rest)
    o_ref = outs[0]
    lse_ref = outs[1] if len(outs) > 1 else None
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [BQ, d]
    block_q = q.shape[0]
    seq_len = k_ref.shape[1]
    num_kb = seq_len // block_k

    # Per-row state lives as [BQ, 1] (2-D sublane-major — what Mosaic
    # vectorizes well) rather than 1-D lane vectors.
    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    # Bottom-right-aligned causal mask (matches _reference's tril with
    # k=lk-lq): query i may see keys up to i + (lk - lq). With lq == lk
    # the offset is 0 (ordinary self-attention).
    lq_total = pl.num_programs(1) * block_q
    offset = seq_len - lq_total
    q_pos = offset + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(kb, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32)  # [BQ, BK]
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        if mask_ref is not None:
            valid = mask_ref[0, :, pl.ds(kb * block_k, block_k)] > 0  # [1, BK]
            if causal:
                # fold causality into the zeroed set: when a row's running
                # max is still _NEG (all visible keys masked so far),
                # exp(_NEG - _NEG) = 1 would resurrect causally-forbidden
                # entries too — the explicit zeroing must cover them
                valid = valid & (q_pos >= k_pos)
            s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        if mask_ref is not None:
            # a fully-masked first block would give exp(_NEG - _NEG) = 1:
            # zero masked entries explicitly (exact, not just numerical)
            p = jnp.where(valid, p, 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.dot(
            p, vblk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    if causal:
        # K blocks strictly above the diagonal contribute nothing — skip:
        # the last needed block holds key index offset + (qi+1)*block_q - 1
        num_kb_eff = jnp.minimum(
            num_kb, (offset + (qi + 1) * block_q - 1) // block_k + 1
        )
    else:
        num_kb_eff = num_kb
    m, l, acc = jax.lax.fori_loop(0, num_kb_eff, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    if lse_ref is not None:  # saved only when a backward will need it
        lse_ref[0] = jax.lax.broadcast_in_dim(
            m + jnp.log(l_safe), (block_q, _LSE_LANES), (0, 1)
        )


# -- backward ----------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, *rest,
    block_k: int, causal: bool, has_mask: bool = False,
):
    if has_mask:
        mask_ref, dq_ref = rest
    else:
        mask_ref, dq_ref = None, rest[0]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [BQ, d]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, :1].astype(jnp.float32)  # [BQ, 1] (lane-broadcast store)
    # D = rowsum(dO ∘ O), computed in-kernel from the blocks already in
    # VMEM — cheaper than materializing a second lane-padded residual.
    dvec = jnp.sum(do * o_ref[0].astype(jnp.float32), axis=-1, keepdims=True)
    block_q = q.shape[0]
    seq_len = k_ref.shape[1]
    num_kb = seq_len // block_k

    lq_total = pl.num_programs(1) * block_q
    offset = seq_len - lq_total
    q_pos = offset + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(kb, acc):
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32)
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        p = jnp.exp(s - lse)  # masked entries: exp(-inf) = 0
        if mask_ref is not None:
            # fully-masked rows have a degenerate lse; zero explicitly —
            # including causally-forbidden entries (see _fwd_kernel)
            valid = mask_ref[0, :, pl.ds(kb * block_k, block_k)] > 0
            if causal:
                valid = valid & (q_pos >= k_pos)
            p = jnp.where(valid, p, 0.0)
        dp = jnp.dot(do, vblk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dvec)
        return acc + jnp.dot(ds, kblk, preferred_element_type=jnp.float32)

    if causal:
        num_kb_eff = jnp.minimum(
            num_kb, (offset + (qi + 1) * block_q - 1) // block_k + 1
        )
    else:
        num_kb_eff = num_kb
    acc0 = jnp.zeros_like(q)
    dq = jax.lax.fori_loop(0, num_kb_eff, body, acc0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, *rest,
    block_q: int, causal: bool, has_mask: bool = False,
):
    if has_mask:
        mask_ref, dk_ref, dv_ref = rest
    else:
        mask_ref, (dk_ref, dv_ref) = None, rest
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)  # [BK, d]
    v = v_ref[0].astype(jnp.float32)
    block_k = k.shape[0]
    seq_q = q_ref.shape[1]
    num_qb = seq_q // block_q
    lk_total = pl.num_programs(1) * block_k
    offset = lk_total - seq_q  # = lk - lq

    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )

    def body(qb, carry):
        dk, dv = carry
        qblk = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        doblk = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        oblk = o_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), :1].astype(jnp.float32)
        dvec = jnp.sum(doblk * oblk, axis=-1, keepdims=True)  # [BQ, 1]
        s = jnp.dot(qblk, k.T, preferred_element_type=jnp.float32)  # [BQ, BK]
        if causal:
            q_pos = offset + qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        p = jnp.exp(s - lse)
        if mask_ref is not None:
            valid = mask_ref[0, :, pl.ds(ki * block_k, block_k)] > 0  # [1, BK]
            if causal:
                # cover causally-forbidden entries resurrected by a
                # degenerate lse (see _fwd_kernel)
                valid = valid & (q_pos >= k_pos)
            p = jnp.where(valid, p, 0.0)
        dv_new = dv + jnp.dot(p.T, doblk, preferred_element_type=jnp.float32)
        dp = jnp.dot(doblk, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dvec)
        dk_new = dk + jnp.dot(ds.T, qblk, preferred_element_type=jnp.float32)
        return dk_new, dv_new

    if causal:
        # q rows below the block's diagonal start: first q block whose last
        # row can see this k block — global q_pos >= k first index
        qb_start = jnp.maximum(0, (ki * block_k - offset) // block_q)
    else:
        qb_start = 0
    zeros = jnp.zeros_like(k)
    dk, dv = jax.lax.fori_loop(qb_start, num_qb, body, (zeros, jnp.zeros_like(v)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# -- reference (XLA) path, used for correctness tests ------------------------


def _reference(q, k, v, causal):
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    )
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((lq, lk), bool), k=lk - lq)
        s = jnp.where(cm[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


# -- plumbing ----------------------------------------------------------------


def _heads_major(x):
    """[b, l, h, d] -> [b*h, l, d]"""
    b, l, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, l, d)


def _heads_minor(x, b, h):
    """[b*h, l, d] -> [b, l, h, d]"""
    bh, l, d = x.shape
    return x.reshape(b, h, l, d).transpose(0, 2, 1, 3)


def _mask_operand(mask, b, h, lk):
    """[b, lk] bool/int key-validity -> ([b, 1, lk] f32 operand, in_spec).
    The singleton middle dim makes the block's last-two dims (1, lk) —
    legal because dim -2 equals the array dim (Mosaic tiling rule)."""
    m = jnp.asarray(mask)
    assert m.shape == (b, lk), (
        f"mask must be [batch, lk] key validity, got {m.shape} for "
        f"batch={b}, lk={lk}"
    )
    operand = m.astype(jnp.float32).reshape(b, 1, lk)
    spec = pl.BlockSpec((1, 1, lk), lambda i, j: (i // h, 0, 0))
    return operand, spec


def _flash_fwd_impl(q, k, v, mask, causal, block_q, block_k, interpret, save_lse=True):
    b, lq, h, d = q.shape
    lk = k.shape[1]
    bq = min(block_q, lq)
    bk = min(block_k, lk)
    assert lq % bq == 0 and lk % bk == 0, (
        f"seq lens ({lq}, {lk}) must divide block sizes ({bq}, {bk})"
    )
    qr, kr, vr = _heads_major(q), _heads_major(k), _heads_major(v)

    in_specs = [
        pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, lk, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, lk, d), lambda i, j: (i, 0, 0)),
    ]
    operands = [qr, kr, vr]
    if mask is not None:
        m_op, m_spec = _mask_operand(mask, b, h, lk)
        operands.append(m_op)
        in_specs.append(m_spec)

    out_shape = [jax.ShapeDtypeStruct((b * h, lq, d), q.dtype)]
    out_specs = [pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0))]
    if save_lse:
        out_shape.append(
            jax.ShapeDtypeStruct((b * h, lq, _LSE_LANES), jnp.float32)
        )
        out_specs.append(
            pl.BlockSpec((1, bq, _LSE_LANES), lambda i, j: (i, j, 0))
        )
    res = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_k=bk, causal=causal, has_mask=mask is not None
        ),
        out_shape=tuple(out_shape),
        grid=(b * h, lq // bq),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        interpret=interpret,
    )(*operands)
    out, lse = res if save_lse else (res[0], None)
    return _heads_minor(out, b, h), lse


def _flash_bwd_impl(q, k, v, mask, o, lse, g, causal, block_q, block_k, interpret):
    b, lq, h, d = q.shape
    lk = k.shape[1]
    bq = min(block_q, lq)
    bk = min(block_k, lk)
    qr, kr, vr = _heads_major(q), _heads_major(k), _heads_major(v)
    dor, orr = _heads_major(g), _heads_major(o)

    mask_ops, mask_specs = [], []
    if mask is not None:
        m_op, m_spec = _mask_operand(mask, b, h, lk)
        mask_ops, mask_specs = [m_op], [m_spec]

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_k=bk, causal=causal, has_mask=mask is not None
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
        grid=(b * h, lq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),  # q
            pl.BlockSpec((1, lk, d), lambda i, j: (i, 0, 0)),  # k
            pl.BlockSpec((1, lk, d), lambda i, j: (i, 0, 0)),  # v
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),  # do
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),  # o
            pl.BlockSpec((1, bq, _LSE_LANES), lambda i, j: (i, j, 0)),  # lse
        ] + mask_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(qr, kr, vr, dor, orr, lse, *mask_ops)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=bq, causal=causal, has_mask=mask is not None
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, lk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, lk, d), v.dtype),
        ),
        grid=(b * h, lk // bk),
        in_specs=[
            pl.BlockSpec((1, lq, d), lambda i, j: (i, 0, 0)),  # q
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0)),  # k
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0)),  # v
            pl.BlockSpec((1, lq, d), lambda i, j: (i, 0, 0)),  # do
            pl.BlockSpec((1, lq, d), lambda i, j: (i, 0, 0)),  # o
            pl.BlockSpec((1, lq, _LSE_LANES), lambda i, j: (i, 0, 0)),  # lse
        ] + mask_specs,
        out_specs=(
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0)),
        ),
        interpret=interpret,
    )(qr, kr, vr, dor, orr, lse, *mask_ops)

    return (
        _heads_minor(dq, b, h),
        _heads_minor(dk, b, h),
        _heads_minor(dv, b, h),
    )


def _on_tpu() -> bool:
    plat = jax.devices()[0].platform
    return plat in ("tpu", "axon")


def auto_flash_attn_fn(attention_impl: str, seq_len: int):
    """THE flash auto-selection policy, shared by every model family's
    ``task_for_mesh``: explicit ``attention_impl == "flash"`` always
    wins; ``"full"`` explicitly pins the XLA path; the default
    (``"auto"``) upgrades to flash on TPU once the sequence crosses
    FLASH_SEQ_THRESHOLD and divides the default q block. Returns
    ``flash_attention`` or None (= use the XLA path). Unknown impl names
    raise — a typo must not silently fall back to XLA attention."""
    if attention_impl == "flash":
        return flash_attention
    if attention_impl == "full":
        return None
    if attention_impl != "auto":
        raise ValueError(
            f"unknown attention_impl {attention_impl!r}; expected one of "
            "'auto', 'full', 'flash', 'ring', 'ulysses'"
        )
    blocks = pick_blocks(seq_len)
    if _on_tpu() and seq_len >= FLASH_SEQ_THRESHOLD and blocks is not None:
        return functools.partial(
            flash_attention, block_q=blocks[0], block_k=blocks[1]
        )
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, mask, causal, block_q, block_k):
    # Primal (inference) path: skip the lse store entirely — pallas
    # outputs aren't DCE'd by XLA, and the (b*h, lq, 128) f32 residual
    # is 4x the bytes of the bf16 output itself.
    out, _ = _flash_fwd_impl(
        q, k, v, mask, causal, block_q, block_k, not _on_tpu(), save_lse=False
    )
    return out


def _flash_fwd(q, k, v, mask, causal, block_q, block_k):
    out, lse = _flash_fwd_impl(
        q, k, v, mask, causal, block_q, block_k, not _on_tpu()
    )
    return out, (q, k, v, mask, out, lse)


def _flash_bwd(causal, block_q, block_k, res, g):
    q, k, v, mask, o, lse = res
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, mask, o, lse, g, causal, block_q, block_k, not _on_tpu()
    )
    return dq, dk, dv, None  # mask is non-differentiable


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,  # [b, lq, h, d], pre-scaled
    k: jax.Array,  # [b, lk, h, d]
    v: jax.Array,  # [b, lk, h, d]
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jax.Array:
    """Drop-in for models.transformer.dot_product_attention. ``mask`` is
    the 2-D ``[batch, lk]`` key-validity form (True = attend); rows whose
    keys are ALL masked produce zero output and zero grads (the XLA
    reference returns a uniform average there — a degenerate case no real
    config hits). Forward AND backward run as Pallas kernels; grads agree
    with the XLA reference to 1e-2 in bf16 (tests/test_flash_attention.py)."""
    if mask is not None and jnp.ndim(mask) != 2:
        raise NotImplementedError(
            "flash attention: only [batch, lk] key-validity masks are "
            f"supported, got shape {jnp.shape(mask)}"
        )
    if causal and q.shape[1] > k.shape[1]:
        # lq > lk leaves some query rows with zero visible keys, where the
        # all-masked-softmax semantics of the kernel (zero output) and the
        # XLA reference (uniform) diverge — a degenerate case; reject it.
        raise ValueError(
            f"causal flash attention requires lq <= lk, got lq={q.shape[1]} "
            f"lk={k.shape[1]}"
        )
    return _flash(q, k, v, mask, causal, block_q, block_k)
