"""Flash attention as a Pallas TPU kernel.

Attention is the one op in the transformer stack where the XLA default
materializes an [L, L] score matrix in HBM; the flash formulation never
does — each (batch*head, q-block) program streams K/V blocks through
VMEM, maintaining the online-softmax running max/denominator, so HBM
traffic is O(L·d) and the MXU sees back-to-back [BQ,d]x[d,BK] and
[BQ,BK]x[BK,d] matmuls (pallas_guide: MXU/VMEM model, grid/BlockSpec).

Forward is the Pallas kernel; backward (custom_vjp) falls back to the
reference XLA attention's gradient — layers already ``jax.checkpoint``
under cfg.remat, so training memory stays bounded while the forward
(the inference/serving hot path and 2/3 of the attention FLOPs under
remat) runs flash. Off-TPU the kernel runs in interpreter mode, which is
how the hermetic CPU tests cover it.

Layout [b, l, h, d] matches models/transformer.py; q must arrive
pre-scaled (by 1/sqrt(d)), exactly like ``dot_product_attention``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [BQ, d]
    block_q = q.shape[0]
    seq_len = k_ref.shape[1]
    num_kb = seq_len // block_k

    m0 = jnp.full((block_q,), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    # Bottom-right-aligned causal mask (matches _reference's tril with
    # k=lk-lq): query i may see keys up to i + (lk - lq). With lq == lk
    # the offset is 0 (ordinary self-attention).
    lq_total = pl.num_programs(1) * block_q
    offset = seq_len - lq_total
    q_pos = offset + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(kb, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32)  # [BQ, BK]
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jnp.dot(
            p, vblk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    if causal:
        # K blocks strictly above the diagonal contribute nothing — skip:
        # the last needed block holds key index offset + (qi+1)*block_q - 1
        num_kb_eff = jnp.minimum(
            num_kb, (offset + (qi + 1) * block_q - 1) // block_k + 1
        )
    else:
        num_kb_eff = num_kb
    m, l, acc = jax.lax.fori_loop(0, num_kb_eff, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _reference(q, k, v, causal):
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    )
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((lq, lk), bool), k=lk - lq)
        s = jnp.where(cm[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    b, lq, h, d = q.shape
    lk = k.shape[1]
    bq = min(block_q, lq)
    bk = min(block_k, lk)
    assert lq % bq == 0 and lk % bk == 0, (
        f"seq lens ({lq}, {lk}) must divide block sizes ({bq}, {bk})"
    )
    # [b, l, h, d] -> [b*h, l, d]
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, lk, d)

    out = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=bk, causal=causal),
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
        grid=(b * h, lq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, lk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, lk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, lq, d).transpose(0, 2, 1, 3)


def _on_tpu() -> bool:
    plat = jax.devices()[0].platform
    return plat in ("tpu", "axon")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k, not _on_tpu())


def _flash_fwd(q, k, v, causal, block_q, block_k):
    return _flash(q, k, v, causal, block_q, block_k), (q, k, v)


def _flash_bwd(causal, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: _reference(a, b, c, causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,  # [b, lq, h, d], pre-scaled
    k: jax.Array,  # [b, lk, h, d]
    v: jax.Array,  # [b, lk, h, d]
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 256,
) -> jax.Array:
    """Drop-in for models.transformer.dot_product_attention (padding
    masks unsupported — pretraining data here is unpadded).

    Default blocks measured on the real chip (BERT-base shapes, L=2048
    causal, chained timing): 3.2 ms vs 6.1 ms for the XLA einsum path —
    ~1.9x; at L=8192 the XLA path OOMs on the [L, L] scores while this
    kernel runs."""
    if mask is not None:
        raise NotImplementedError(
            "flash attention: padding masks not supported; pass mask=None"
        )
    if causal and q.shape[1] > k.shape[1]:
        # lq > lk leaves some query rows with zero visible keys, where the
        # all-masked-softmax semantics of the kernel (zero output) and the
        # XLA reference (uniform) diverge — a degenerate case; reject it.
        raise ValueError(
            f"causal flash attention requires lq <= lk, got lq={q.shape[1]} "
            f"lk={k.shape[1]}"
        )
    return _flash(q, k, v, causal, block_q, block_k)
