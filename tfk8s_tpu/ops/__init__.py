"""Hand-written TPU kernels (pallas) for hot ops the XLA autofuser
doesn't already win on. The reference has no numerical code at all
(SURVEY.md §2: operator treats training as a black box) — this layer is
the build's TPU-native data-plane addition."""

from tfk8s_tpu.ops.flash_attention import flash_attention  # noqa: F401
from tfk8s_tpu.ops.group_norm import fused_group_norm  # noqa: F401
