"""Fused single-pass GroupNorm(+ReLU) Pallas kernel, with the measured
verdict on when to use it (PERF_RESNET.md).

The kernel does GroupNorm + affine + optional ReLU in ONE sweep: each
grid step pulls a single sample's [H, W, C] activation slice into VMEM,
computes the per-group statistics, normalizes, and writes the result —
1 HBM read + 1 write, vs 3 touches (stats read / normalize read / write)
for a standalone XLA GroupNorm. The backward kernel fuses the three
reduction families (per-group dxhat moments, per-channel dγ/dβ) into a
single dy+x read and one dx write, recomputing the ReLU mask in-register
from x and the saved statistics (no extra saved tensor). Group
reductions use a tiny one-hot matmul ([1,C] @ [C,G]) instead of
reshapes: group size can be < 128 lanes, and Mosaic relayouts of
lane-unaligned reshapes are slower than an MXU flick at this size.

**Measured verdict (v5e via axon, batch 256 — full numbers in
PERF_RESNET.md):** standalone, the kernel matches XLA's 3-pass GN on
fat-channel shapes (4.89 vs 4.81 ms on [256,56,56,256]) and loses where
C < 128 wastes lanes. INSIDE ResNet-50 it regresses the step 2.5×
(106.6 → 261.8 ms): a ``pallas_call`` is an opaque fusion boundary, so
it forces the conv output to materialize where XLA otherwise fuses the
stats reduction into the producing conv's epilogue and the normalize
into the consumer — XLA's in-model marginal GN cost (~1.4 passes) is
below this kernel's theoretical 2-pass floor. The model therefore keeps
``nn.GroupNorm``; this kernel remains the right tool where a norm is
NOT adjacent to fusable producers/consumers (e.g. a standalone
normalization pass over stored activations).

Reference counterpart: none — the reference delegates models entirely
(k8s-operator.md:6). Numerics match ``flax.linen.GroupNorm`` (f32
statistics, biased variance, eps inside the sqrt) so the flax module and
this kernel are interchangeable per-call-site.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _on_tpu() -> bool:
    plat = jax.devices()[0].platform
    return plat in ("tpu", "axon")


def _group_matrices(channels: int, groups: int):
    """One-hot membership matrices: M[c, g] = 1 if channel c is in group
    g (contiguous blocks, the flax convention), and its transpose."""
    gs = channels // groups
    c = lax.broadcasted_iota(jnp.int32, (channels, groups), 0)
    g = lax.broadcasted_iota(jnp.int32, (channels, groups), 1)
    m_cg = (c // gs == g).astype(jnp.float32)
    c2 = lax.broadcasted_iota(jnp.int32, (groups, channels), 1)
    g2 = lax.broadcasted_iota(jnp.int32, (groups, channels), 0)
    m_gc = (c2 // gs == g2).astype(jnp.float32)
    return m_cg, m_gc


# -- forward -----------------------------------------------------------------


def _fwd_kernel(x_ref, scale_ref, bias_ref, y_ref, mean_ref, rstd_ref,
                *, groups: int, eps: float, relu: bool):
    hw = x_ref.shape[1] * x_ref.shape[2]
    c = x_ref.shape[3]
    n = float(hw * (c // groups))
    xf = x_ref[0].reshape(hw, c).astype(jnp.float32)

    m_cg, m_gc = _group_matrices(c, groups)
    s = jnp.sum(xf, axis=0, keepdims=True)          # [1, C]
    mean = jnp.dot(s, m_cg, preferred_element_type=jnp.float32) / n  # [1, G]
    mean_c = jnp.dot(mean, m_gc, preferred_element_type=jnp.float32)  # [1, C]
    # two-pass variance E[(x-mean)^2] over the VMEM-resident tile (an
    # extra VPU sweep, zero extra HBM): the one-pass E[x^2]-mean^2 form
    # cancels catastrophically in f32 when |mean| >> std, which would
    # break the flax-interchangeability claim on large-mean activations
    d = xf - mean_c
    ss = jnp.sum(d * d, axis=0, keepdims=True)      # [1, C]
    var = jnp.dot(ss, m_cg, preferred_element_type=jnp.float32) / n   # [1, G]
    rstd = lax.rsqrt(var + eps)
    rstd_c = jnp.dot(rstd, m_gc, preferred_element_type=jnp.float32)  # [1, C]
    gamma = scale_ref[0].reshape(1, c).astype(jnp.float32)
    beta = bias_ref[0].reshape(1, c).astype(jnp.float32)
    y = d * rstd_c * gamma + beta
    if relu:
        y = jnp.maximum(y, 0.0)
    y_ref[0] = y.astype(y_ref.dtype).reshape(x_ref.shape[1:])
    mean_ref[0] = mean.reshape(1, 1, groups)
    rstd_ref[0] = rstd.reshape(1, 1, groups)


def _fwd_impl(x, scale, bias, groups, eps, relu, interpret):
    b, h, w, c = x.shape
    scale2 = scale.reshape(1, c)
    bias2 = bias.reshape(1, c)
    kern = functools.partial(_fwd_kernel, groups=groups, eps=eps, relu=relu)
    y, mean, rstd = pl.pallas_call(
        kern,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, groups), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, groups), lambda i: (i, 0, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, w, c), x.dtype),
            # TPU blocks need their trailing dims to tile the array; a
            # [B, G] output with (1, G) blocks does not (sublane 1 vs B),
            # so the per-sample stats ride as [B, 1, 1, G]
            jax.ShapeDtypeStruct((b, 1, 1, groups), jnp.float32),
            jax.ShapeDtypeStruct((b, 1, 1, groups), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            # f32 temps for an 800k-element block exceed the default 16MB
            # scoped-vmem cap; raise it toward the physical budget
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(x, scale2, bias2)
    return y, mean.reshape(b, groups), rstd.reshape(b, groups)


# -- backward ----------------------------------------------------------------


def _bwd_kernel(dy_ref, x_ref, scale_ref, bias_ref, mean_ref, rstd_ref,
                dx_ref, dg_ref, db_ref, *, groups: int, relu: bool):
    hw = x_ref.shape[1] * x_ref.shape[2]
    c = x_ref.shape[3]
    n = float(hw * (c // groups))
    m_cg, m_gc = _group_matrices(c, groups)

    xf = x_ref[0].reshape(hw, c).astype(jnp.float32)
    dy = dy_ref[0].reshape(hw, c).astype(jnp.float32)
    gamma = scale_ref[0].reshape(1, c).astype(jnp.float32)
    mean_c = jnp.dot(
        mean_ref[0, 0].reshape(1, groups), m_gc,
        preferred_element_type=jnp.float32,
    )
    rstd_c = jnp.dot(
        rstd_ref[0, 0].reshape(1, groups), m_gc,
        preferred_element_type=jnp.float32,
    )
    xhat = (xf - mean_c) * rstd_c
    if relu:
        beta = bias_ref[0].reshape(1, c).astype(jnp.float32)
        # recompute the pre-ReLU output's sign from x + stats: no extra
        # saved tensor, no extra HBM read
        mask = (xhat * gamma + beta) > 0.0
        dz = jnp.where(mask, dy, 0.0)
    else:
        dz = dy

    dxhat = dz * gamma
    # the two per-group moments and the two per-channel param grads, all
    # from the same resident tile
    s1 = jnp.dot(
        jnp.sum(dxhat, axis=0, keepdims=True), m_cg,
        preferred_element_type=jnp.float32,
    ) / n                                                     # [1, G]
    s2 = jnp.dot(
        jnp.sum(dxhat * xhat, axis=0, keepdims=True), m_cg,
        preferred_element_type=jnp.float32,
    ) / n                                                     # [1, G]
    s1_c = jnp.dot(s1, m_gc, preferred_element_type=jnp.float32)
    s2_c = jnp.dot(s2, m_gc, preferred_element_type=jnp.float32)
    dx = rstd_c * (dxhat - s1_c - xhat * s2_c)
    dx_ref[0] = dx.astype(dx_ref.dtype).reshape(x_ref.shape[1:])
    dg_ref[0] = jnp.sum(dz * xhat, axis=0).reshape(1, 1, c)
    db_ref[0] = jnp.sum(dz, axis=0).reshape(1, 1, c)


def _bwd_impl(dy, x, scale, bias, mean, rstd, groups, relu, interpret):
    b, h, w, c = x.shape
    kern = functools.partial(_bwd_kernel, groups=groups, relu=relu)
    dx, dg_p, db_p = pl.pallas_call(
        kern,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, 1, 1, groups), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, groups), lambda i: (i, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, c), lambda i: (i, 0, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, w, c), x.dtype),
            jax.ShapeDtypeStruct((b, 1, 1, c), jnp.float32),
            jax.ShapeDtypeStruct((b, 1, 1, c), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(dy, x, scale.reshape(1, c), bias.reshape(1, c),
      mean.reshape(b, 1, 1, groups), rstd.reshape(b, 1, 1, groups))
    # tiny [B, C] partial reductions finish in XLA
    return dx, jnp.sum(dg_p, axis=(0, 1, 2)), jnp.sum(db_p, axis=(0, 1, 2))


# -- custom_vjp wiring -------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused(x, scale, bias, groups, eps, relu, interpret):
    y, _, _ = _fwd_impl(x, scale, bias, groups, eps, relu, interpret)
    return y


def _fused_fwd(x, scale, bias, groups, eps, relu, interpret):
    y, mean, rstd = _fwd_impl(x, scale, bias, groups, eps, relu, interpret)
    return y, (x, scale, bias, mean, rstd)


def _fused_bwd(groups, eps, relu, interpret, res, dy):
    x, scale, bias, mean, rstd = res
    dx, dg, db = _bwd_impl(
        dy, x, scale, bias, mean, rstd, groups, relu, interpret
    )
    return dx, dg.astype(scale.dtype), db.astype(bias.dtype)


_fused.defvjp(_fused_fwd, _fused_bwd)


# -- public API --------------------------------------------------------------


def reference_group_norm(x, scale, bias, groups: int, eps: float = 1e-6,
                         relu: bool = False):
    """Plain-XLA GroupNorm(+ReLU), flax-equivalent numerics (f32 stats,
    biased variance). The off-TPU path and the kernel's test oracle."""
    b = x.shape[0]
    c = x.shape[-1]
    spatial = x.shape[1:-1]
    xf = x.astype(jnp.float32).reshape(b, -1, groups, c // groups)
    mean = jnp.mean(xf, axis=(1, 3), keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=(1, 3), keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    y = y.reshape(b, *spatial, c) * scale.astype(jnp.float32) + bias.astype(
        jnp.float32
    )
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


def fused_group_norm(
    x: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    groups: int,
    eps: float = 1e-6,
    relu: bool = False,
    use_pallas: Optional[bool] = None,
) -> jax.Array:
    """GroupNorm(+optional ReLU) over the channel-last dim of an NHWC
    tensor. On TPU this is the single-pass Pallas kernel (1 HBM read + 1
    write vs XLA's 3 touches); elsewhere the XLA reference. Differentiable
    either way."""
    if x.ndim != 4:
        raise NotImplementedError(
            f"fused_group_norm expects NHWC rank-4 input, got shape {x.shape}"
        )
    c = x.shape[-1]
    if c % groups:
        raise ValueError(f"channels {c} not divisible by groups {groups}")
    if use_pallas is None:
        use_pallas = _on_tpu()
    if not use_pallas:
        return reference_group_norm(x, scale, bias, groups, eps, relu)
    return _fused(x, scale, bias, groups, float(eps), bool(relu), False)


def fused_group_norm_interpret(x, scale, bias, groups, eps=1e-6, relu=False):
    """Interpreter-mode kernel execution (CPU tests of the kernel path)."""
    return _fused(x, scale, bias, groups, float(eps), bool(relu), True)
