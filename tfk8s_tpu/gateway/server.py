"""TPUGateway HTTP server: the one wire entrance for inference traffic.

Same transport stack and idioms as ``client/apiserver.py`` (threaded
``http.server``, HTTP/1.1 keep-alive, per-request latency histograms,
``metav1.Status`` error envelopes) serving one route::

    POST /v1/serve/<namespace>/<name>
        body:    {"payload": <JSON payload>, "timeoutS": <float, opt>}
        headers: X-Tenant: <tenant id>        (default "default")
        200:     {"result": <model response>}

Status-code matrix (every error is typed — the ServeError taxonomy on
the wire; shed responses ALWAYS carry Retry-After)::

    400 InvalidRequest    unservable request (never retried)
    404 NotFound          no such TPUServe
    429 QuotaExceeded     the TENANT's bucket/concurrency budget
    429 Overloaded        cluster pressure (priority shed or replica queue)
    500 RequestFailed     model raised executing the batch
    503 Unavailable       no routable replica held until the deadline, OR
                          a replica failed mid-flight with the retry
                          budget exhausted (retriable by the caller)
    504 DeadlineExceeded  deadline elapsed while queued/executing
                          (``details.triedReplicas`` names the replicas
                          the dispatch loop burned the deadline on)

``Retry-After`` uses fractional seconds (e.g. ``0.087``): sub-second
backoff is the natural timescale of a batching queue and this is our
own client on both ends; integer-second rounding would quantize every
backoff to >= 1 s and idle the fleet. The Draining replicas a rollout
produces are never surfaced: the router drops them at drain start (the
in-process drain hook) and the dispatch loop retries the next-least-
loaded replica inside the caller's deadline — the wire keeps the
zero-failed-request contract the in-process client already had.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

from tfk8s_tpu.client.ratelimit import TokenBucketRateLimiter
from tfk8s_tpu.client.store import NotFound, Unavailable
from tfk8s_tpu.gateway.admission import TenantAdmission
from tfk8s_tpu.gateway.affinity import affinity_key_of
from tfk8s_tpu.gateway.router import RouteTable
from tfk8s_tpu.obs.trace import TailSampler, get_tracer, recent_request_traces
from tfk8s_tpu.runtime import server as serving
from tfk8s_tpu.runtime.handoff import (
    HandoffError,
    KVTransport,
    LocalKVTransport,
)
from tfk8s_tpu.runtime.kvtier import CacheDirectory
from tfk8s_tpu.runtime.server import (
    DeadlineExceeded,
    Draining,
    InvalidRequest,
    Overloaded,
    Preempted,
    QuotaExceeded,
    ReplicaUnavailable,
    ServeError,
    lookup_replica,
)
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("gateway")

# how long a fetched TPUServe spec (tenancy, queue limit) stays fresh
SPEC_TTL_S = 1.0
DEFAULT_TENANT = "default"
# server-side ceiling on a single request's deadline
MAX_TIMEOUT_S = 120.0
# Retry-After when a replica shed without a hint of its own
DEFAULT_RETRY_AFTER_S = 0.1
# in-flight recovery (ISSUE 13): transport-class re-dispatch attempts
# per request, AND a per-serve token bucket bounding the fleet-wide
# retry rate — a dying fleet must not amplify offered load into a
# retry storm
MAX_DISPATCH_RETRIES = 3
RETRY_BUDGET_QPS = 20.0
RETRY_BUDGET_BURST = 40


def _err_body(status: int, reason: str, message: str,
              details: Optional[Dict[str, Any]] = None) -> bytes:
    # the k8s metav1.Status failure envelope (apiserver parity)
    body = {
        "kind": "Status",
        "apiVersion": "v1",
        "status": "Failure",
        "code": status,
        "reason": reason,
        "message": message,
    }
    if details:
        body["details"] = details
    return json.dumps(body).encode()


def _wire_error(exc: Exception) -> Tuple[int, str, Dict[str, Any], Dict[str, str]]:
    """Map a typed error to (status, reason, details, extra_headers) —
    the one place the taxonomy meets HTTP status codes."""
    headers: Dict[str, str] = {}
    if isinstance(exc, QuotaExceeded):
        headers["Retry-After"] = f"{exc.retry_after_s:.3f}"
        return 429, "QuotaExceeded", {
            "tenant": exc.tenant,
            "quota": exc.reason,
            "retryAfterS": round(exc.retry_after_s, 3),
        }, headers
    if isinstance(exc, Overloaded):
        retry = exc.retry_after_s or DEFAULT_RETRY_AFTER_S
        headers["Retry-After"] = f"{retry:.3f}"
        return 429, "Overloaded", {
            "queueDepth": exc.queue_depth,
            "queueLimit": exc.queue_limit,
            "retryAfterS": round(retry, 3),
        }, headers
    if isinstance(exc, InvalidRequest):
        return 400, "InvalidRequest", {}, headers
    if isinstance(exc, NotFound):
        return 404, "NotFound", {}, headers
    if isinstance(exc, Unavailable):
        return 503, "Unavailable", _tried_details(exc), headers
    if isinstance(exc, DeadlineExceeded):
        return 504, "DeadlineExceeded", _tried_details(exc), headers
    if isinstance(exc, ReplicaUnavailable):
        # transport-class: the replica died mid-flight and the retry
        # budget ran out — retriable by the caller, NOT a model failure
        return 503, "Unavailable", _tried_details(exc), headers
    if isinstance(exc, Preempted):
        # the row was evicted for a higher-priority admission and its
        # spill failed — nothing about the request is suspect, the
        # caller may simply resubmit (503, retriable, like a shed)
        return 503, "Preempted", {}, headers
    if isinstance(exc, HandoffError):
        # the decode pool refused the prefill pool's KV buffer (version
        # skew mid-rollout, geometry mismatch, integrity failure): a
        # between-replicas failure, not the caller's and not the model's
        return 502, "HandoffFailed", {}, headers
    # Draining should be absorbed by the dispatch loop; RequestFailed and
    # any other ServeError are the model's failure, a plain 500
    return 500, "RequestFailed", {}, headers


def _tried_details(exc: Exception) -> Dict[str, Any]:
    """The replicas the dispatch loop burned the deadline on, for the
    Status envelope details — pinned by tests/test_gateway_faults.py."""
    tried = getattr(exc, "tried", None)
    return {"triedReplicas": list(tried)} if tried else {}


def debug_requests(tracer, inflight: Optional[list] = None,
                   trace_id: Optional[str] = None,
                   limit: int = 32) -> Dict[str, Any]:
    """The ``/debug/requests`` zpage body: in-flight requests plus the
    recently tail-sampled request timelines — one shape shared by the
    gateway, the apiserver, and the operator server."""
    return {
        "inflight": list(inflight or []),
        "recent": recent_request_traces(
            tracer, trace_id=trace_id, limit=limit
        ),
        "spans_dropped": dict(tracer.dropped),
    }


def debug_decode() -> Dict[str, Any]:
    """The ``/debug/decode`` zpage body: live slot/page occupancy per
    registered replica (decode loops report slots; batchers their
    queue)."""
    replicas: Dict[str, Any] = {}
    for key in serving.replica_keys():
        server = serving.lookup_replica(key)
        if server is None:
            continue
        state_fn = getattr(server, "debug_state", None)
        if state_fn is not None:
            replicas[key] = state_fn()
    return {"replicas": replicas}


class _LeanHeaders(dict):
    """Header mapping with case-insensitive ``get`` — keys are stored
    lowercased by the fast-path parser below."""

    def get(self, key, default=None):  # type: ignore[override]
        return dict.get(self, key.lower(), default)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # response header block and body are separate send()s: without
    # TCP_NODELAY, Nagle + the peer's delayed ACK stalls the tail of
    # every response ~40ms — dwarfing the actual serving latency
    disable_nagle_algorithm = True
    server: "GatewayServer"

    # Date header cache: (whole_second, formatted) — strftime per response
    # is measurable at saturation and the value only changes once a second
    _date_cache = (-1, "")

    def log_message(self, *a):  # route through our logger, debug level
        log.debug("http: " + a[0], *a[1:])

    def parse_request(self) -> bool:
        """Fast-path request parse for the serving hot loop.

        The stdlib implementation funnels every request's headers through
        the email-package parser (~100us on one core — a third of the
        whole wire budget at saturation). Plain ``HTTP/1.x`` requests take
        a split/partition parse instead; anything unusual falls back to
        the stdlib parser before any header bytes are consumed.
        """
        self.close_connection = True
        try:
            requestline = self.raw_requestline.decode("latin-1")
            command, path, version = requestline.rstrip("\r\n").split(" ")
        except (UnicodeDecodeError, ValueError):
            return super().parse_request()
        if version not in ("HTTP/1.1", "HTTP/1.0"):
            return super().parse_request()
        self.requestline = requestline.rstrip("\r\n")
        self.command, self.path, self.request_version = command, path, version
        headers = _LeanHeaders()
        rfile = self.rfile
        while True:
            line = rfile.readline(65537)
            if line in (b"\r\n", b"\n", b""):
                break
            if len(line) > 65536:
                self.send_error(431)
                return False
            name, sep, value = line.partition(b":")
            if sep:
                headers[name.decode("latin-1").strip().lower()] = (
                    value.decode("latin-1").strip()
                )
        self.headers = headers
        conntype = (headers.get("connection") or "").lower()
        if conntype == "close":
            self.close_connection = True
        elif version == "HTTP/1.1":
            self.close_connection = False
        else:
            self.close_connection = conntype != "keep-alive"
        if (headers.get("expect", "").lower() == "100-continue"
                and version == "HTTP/1.1"):
            if not self.handle_expect_100():
                return False
        return True

    def date_time_string(self, timestamp=None):  # type: ignore[override]
        if timestamp is not None:
            return super().date_time_string(timestamp)
        now = int(time.time())
        cached = _Handler._date_cache
        if cached[0] == now:
            return cached[1]
        value = super().date_time_string(now)
        _Handler._date_cache = (now, value)
        return value

    def _send_json(self, status: int, payload: Any,
                   extra_headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_status_error(
        self, status: int, reason: str, message: str,
        details: Optional[Dict[str, Any]] = None,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> None:
        body = _err_body(status, reason, message, details)
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:
        if self.path == "/healthz":
            self._send_json(200, {"status": "ok"})
            return
        path, _, query = self.path.partition("?")
        if path == "/debug/requests":
            params = dict(
                kv.split("=", 1) for kv in query.split("&") if "=" in kv
            )
            self._send_json(200, debug_requests(
                get_tracer(), self.server.inflight_snapshot(),
                trace_id=params.get("trace_id"),
                limit=int(params.get("limit", "32")),
            ))
            return
        if path == "/debug/decode":
            self._send_json(200, debug_decode())
            return
        if path == "/debug/routes":
            self._send_json(200, self.server.debug_routes())
            return
        self._send_status_error(404, "NotFound", self.path)

    def do_POST(self) -> None:
        parts = [p for p in self.path.split("/") if p]
        if len(parts) != 4 or parts[0] != "v1" or parts[1] != "serve":
            self._send_status_error(404, "NotFound", self.path)
            return
        namespace, name = parts[2], parts[3]
        tenant = self.headers.get("X-Tenant", "").strip() or DEFAULT_TENANT
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, TypeError):
            self._send_status_error(400, "BadRequest", "body must be JSON")
            return
        timeout = min(float(body.get("timeoutS") or 30.0), MAX_TIMEOUT_S)
        serve_label = f"{namespace}/{name}"
        m = self.server.metrics
        t0 = time.perf_counter()
        result = None
        err: Optional[Exception] = None
        code = 200
        # the request's ROOT span: continues the client's traceparent
        # header and anchors the trace's tail-sampling verdict at its end
        tracer = get_tracer()
        span = tracer.start_span(
            "gateway.request",
            traceparent=self.headers.get("traceparent"),
            attributes={"serve": serve_label, "tenant": tenant},
            tail_sample=True,
        )
        self.server.track_inflight(span, serve_label, tenant)
        meta: Dict[str, str] = {}
        try:
            with span:
                try:
                    result = self.server.dispatch(
                        namespace, name, tenant, body.get("payload"), timeout,
                        session=self.headers.get("x-tfk8s-session"),
                        meta=meta,
                    )
                except Exception as exc:  # noqa: BLE001 - typed wire errors
                    err = exc
                    code, reason, details, headers = _wire_error(exc)
                    if not isinstance(exc, (ServeError, NotFound, Unavailable)):
                        log.warning("gateway 500 on %s: %s", serve_label, exc)
                span.set_attribute("http.status_code", code)
                if err is not None:
                    span.set_status("error", f"{reason}: {err}")
        finally:
            self.server.untrack_inflight(span)
        # metrics land BEFORE the response bytes: a caller observing its
        # own 200 must find the series already incremented
        if m is not None:
            labels = {"serve": serve_label, "tenant": tenant}
            # exemplar only when the tail sampler KEPT the trace — a
            # bucket must never link to a trace that was dropped
            kept = tracer.verdict(span.trace_id) is True
            m.observe(
                "tfk8s_gateway_request_seconds",
                time.perf_counter() - t0, labels,
                exemplar=span.trace_id if kept else None,
            )
            m.inc("tfk8s_gateway_requests_total", 1.0,
                  {**labels, "code": str(code)})
            if code == 429:
                m.inc("tfk8s_gateway_shed_total", 1.0, {
                    **labels,
                    "reason": getattr(err, "shed_reason", None)
                    or getattr(err, "reason", None) or "overloaded",
                })
        if err is None:
            # disaggregated serves hand the caller its routing pin: echo
            # the session token on follow-up requests to stay affine to
            # the replica holding the conversation's warm KV prefix
            self._send_json(200, {"result": result}, extra_headers=(
                {"x-tfk8s-session": meta["session"]}
                if meta.get("session") else None
            ))
        else:
            self._send_status_error(code, reason, str(err), details, headers)


class _ServeState:
    """Per-TPUServe routing + admission, plus the TTL-cached spec bits
    the hot path needs (queue limit, tenancy). A disaggregated serve
    carries TWO route tables — one per phase pool, each discovering only
    its pool's pods; ``table`` aliases the prefill table there so the
    admission pressure signal reads the pool requests enter first."""

    __slots__ = ("table", "admission", "queue_limit", "fetched",
                 "retry_budget", "prefill", "decode", "page_size",
                 "kv_dir")

    def __init__(self, table: RouteTable,
                 prefill: Optional[RouteTable] = None,
                 decode: Optional[RouteTable] = None):
        self.table = table
        self.prefill = prefill
        self.decode = decode
        self.page_size = 0
        # cache directory (runtime/kvtier): present only when the serve
        # carries a KVTierPolicy — absent means ZERO directory traffic
        self.kv_dir: Optional[CacheDirectory] = None
        self.admission = TenantAdmission()
        self.queue_limit = 0
        self.fetched = 0.0
        # transport-failure re-dispatches debit this bucket (fleet-wide
        # per serve) — exhausted means the failure surfaces typed
        self.retry_budget = TokenBucketRateLimiter(
            RETRY_BUDGET_QPS, RETRY_BUDGET_BURST
        )

    @property
    def disagg(self) -> bool:
        return self.prefill is not None

    def named_tables(self) -> list:
        if self.prefill is not None:
            return [("prefill", self.prefill), ("decode", self.decode)]
        return [("", self.table)]


class GatewayServer(ThreadingHTTPServer):
    """Threaded HTTP serving front door over one clientset. ``port=0``
    binds an ephemeral port (tests); ``serve_background()`` runs on a
    daemon thread and returns the bound port."""

    daemon_threads = True
    # an open-loop load generator keeps many keep-alive connections
    request_queue_size = 128

    def __init__(self, clientset, host: str = "127.0.0.1", port: int = 0,
                 metrics=None):
        self._cs = clientset
        self.metrics = metrics
        if metrics is not None:
            metrics.describe(
                "tfk8s_gateway_request_seconds",
                "End-to-end wall time per gateway request, by serve/tenant.",
            )
            metrics.describe(
                "tfk8s_gateway_queue_seconds",
                "Admission + routing delay before a request's final "
                "dispatch to a replica.",
            )
            metrics.describe(
                "tfk8s_gateway_shed_total",
                "Requests shed with a typed 429, by tenant and reason "
                "(qps/concurrency/priority/overloaded).",
            )
            metrics.describe(
                "tfk8s_gateway_requests_total",
                "Gateway requests answered, by serve/tenant/status code.",
            )
            metrics.describe(
                "tfk8s_gateway_route_replicas",
                "Routable replicas in the route table, per serve.",
            )
            metrics.describe(
                "tfk8s_gateway_route_depth",
                "Least effective queue depth across routable replicas.",
            )
            metrics.describe(
                "tfk8s_gateway_ejections_total",
                "Replicas ejected from the routing set by the health "
                "state machine, by reason "
                "(errors/deadline/gray/probe).",
            )
            metrics.describe(
                "tfk8s_gateway_retries_total",
                "In-flight re-dispatches to a surviving replica, by "
                "reason (draining/transport).",
            )
            metrics.describe(
                "tfk8s_gateway_replica_removed_total",
                "Replicas removed from the route table, by reason "
                "(stale/drained/ejected).",
            )
            metrics.describe(
                "tfk8s_gateway_affinity_requests_total",
                "Affinity-routed picks, by route "
                "(affine=ring owner, spill=owner too deep, none=no key).",
            )
            metrics.describe(
                "tfk8s_gateway_affinity_ring_members",
                "Replicas on the prefix-affinity consistent-hash ring.",
            )
            metrics.describe(
                "tfk8s_gateway_kv_directory_total",
                "Cache-directory lookups on the dispatch path, by "
                "outcome (hit=fresh owner, stale=only expired reports, "
                "miss=no replica reported the prefix).",
            )
            metrics.describe(
                "tfk8s_disagg_handoffs_total",
                "Prefill->decode KV handoffs brokered by the gateway, "
                "by outcome.",
            )
            metrics.describe(
                "tfk8s_disagg_handoff_seconds",
                "Wall time of one KV handoff transfer (serialize + "
                "verify + deserialize).",
            )
            metrics.describe(
                "tfk8s_disagg_handoff_bytes",
                "Wire size of one KV handoff buffer.",
            )
        self.stopping = threading.Event()
        self._states: Dict[Tuple[str, str], _ServeState] = {}
        self._states_lock = threading.Lock()
        # request-scoped tracing: install a tail sampler on the process
        # tracer (request roots only — control-plane spans bypass it) and
        # wire the drop counter into this gateway's registry
        tracer = get_tracer()
        if tracer.sampler is None:
            tracer.set_sampler(TailSampler())
        if metrics is not None:
            tracer.set_metrics(metrics)
        # in-flight request table for /debug/requests (span id -> row)
        self._inflight: Dict[str, Dict[str, Any]] = {}
        self._inflight_lock = threading.Lock()
        # the KV handoff seam: one box, the transfer is a serialize/
        # verify/deserialize memcpy; a real-TPU deployment injects a
        # device-to-device KVTransport here instead
        self.transport: KVTransport = LocalKVTransport()
        # route tables learn of drains the instant replicas unregister
        self._drain_hook: Callable[[str], None] = self._on_drain
        serving.add_drain_hook(self._drain_hook)
        super().__init__((host, port), _Handler)

    # -- lifecycle -----------------------------------------------------------

    @property
    def port(self) -> int:
        return self.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.server_address[0]}:{self.port}"

    def serve_background(self) -> int:
        t = threading.Thread(target=self.serve_forever, daemon=True,
                             name="gateway")
        t.start()
        return self.port

    def shutdown(self) -> None:  # type: ignore[override]
        self.stopping.set()
        serving.remove_drain_hook(self._drain_hook)
        super().shutdown()

    def _on_drain(self, key: str) -> None:
        with self._states_lock:
            tables = [
                t for s in self._states.values()
                for _, t in s.named_tables()
            ]
        for table in tables:
            table.mark_draining(key)

    # -- /debug/requests in-flight table -------------------------------------

    def track_inflight(self, span, serve: str, tenant: str) -> None:
        if not span.span_id:
            return  # tracing disabled: _NoopSpan
        with self._inflight_lock:
            self._inflight[span.span_id] = {
                "trace_id": span.trace_id,
                "span_id": span.span_id,
                "serve": serve,
                "tenant": tenant,
                "start_time": span.start_time,
            }

    def untrack_inflight(self, span) -> None:
        if not span.span_id:
            return
        with self._inflight_lock:
            self._inflight.pop(span.span_id, None)

    def inflight_snapshot(self) -> list:
        now = time.time()
        with self._inflight_lock:
            rows = [dict(r) for r in self._inflight.values()]
        for r in rows:
            r["age_s"] = now - r["start_time"]
        return sorted(rows, key=lambda r: r["start_time"])

    # -- request path --------------------------------------------------------

    def state_for(self, namespace: str, name: str) -> _ServeState:
        """The (ns, name) routing/admission state, spec-refreshed within
        SPEC_TTL_S. Raises store.NotFound for an unknown TPUServe."""
        now = time.monotonic()
        with self._states_lock:
            state = self._states.get((namespace, name))
        if state is not None and now - state.fetched < SPEC_TTL_S:
            return state
        try:
            serve = self._cs.tpuserves(namespace).get(name)
        except NotFound:
            with self._states_lock:
                self._states.pop((namespace, name), None)
            raise
        disagg = serve.spec.disaggregation is not None
        with self._states_lock:
            state = self._states.get((namespace, name))
            if state is None or state.disagg != disagg:
                # (re)build: flipping disaggregation on/off swaps the
                # routing topology wholesale (the pods rolled anyway —
                # the block is part of the template hash)
                if disagg:
                    prefill = RouteTable(
                        self._cs, name, namespace, metrics=self.metrics,
                        phase="prefill", affinity=True,
                    )
                    decode = RouteTable(
                        self._cs, name, namespace, metrics=self.metrics,
                        phase="decode",
                    )
                    state = _ServeState(prefill, prefill=prefill,
                                        decode=decode)
                else:
                    state = _ServeState(RouteTable(
                        self._cs, name, namespace, metrics=self.metrics,
                    ))
                self._states[(namespace, name)] = state
            state.queue_limit = serve.spec.batching.queue_limit
            state.page_size = serve.spec.batching.page_size
            kv = getattr(serve.spec, "kv_tier", None)
            if kv is None:
                # policy absent: no directory, no polling — the serving
                # path is bit-identical to a pre-kvtier gateway
                state.kv_dir = None
            elif state.kv_dir is None:
                state.kv_dir = CacheDirectory(ttl_s=kv.directory_ttl_s)
            state.fetched = now
        state.admission.configure(serve.spec.tenancy)
        return state

    def _count_retry(self, serve: str, tenant: str, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.inc("tfk8s_gateway_retries_total", 1.0, {
                "serve": serve, "tenant": tenant, "reason": reason,
            })

    def dispatch(self, namespace: str, name: str, tenant: str,
                 payload: Any, timeout: float,
                 session: Optional[str] = None,
                 meta: Optional[Dict[str, str]] = None) -> Any:
        """Admit, route least-loaded, submit; absorb Draining, vanished,
        and CRASHED replicas by re-routing to a survivor inside the
        deadline. A serve request is idempotent (a pure function of its
        payload), so a mid-flight transport failure is retriable —
        bounded per request by MAX_DISPATCH_RETRIES and fleet-wide by
        the serve's token-bucket retry budget. Every attempt's outcome
        feeds the router's health state machine.

        Disaggregated serves take the two-phase path instead: affine
        prefill, gateway-brokered KV handoff, least-loaded decode.
        ``session`` is the caller's sticky token; ``meta`` (when given)
        returns ``{"session": key}`` for the response header."""
        state = self.state_for(namespace, name)
        if state.disagg:
            return self._dispatch_disagg(
                state, namespace, name, tenant, payload, timeout,
                session=session, meta=meta,
            )
        serve_label = f"{namespace}/{name}"
        deadline = time.monotonic() + timeout
        t0 = time.perf_counter()
        # the handler's root span is ambient on this thread; its context
        # rides into the replica submit so the decode loop's timeline
        # lands in the SAME trace
        span = get_tracer().current_span()
        traceparent = span.traceparent if span is not None else None
        priority = state.admission.priority_of(tenant)
        release = state.admission.admit(
            tenant, state.table.least_depth(), state.queue_limit
        )
        try:
            exclude: set = set()
            tried: list = []
            transport_retries = 0
            backoff = 0.005
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    exc = DeadlineExceeded(
                        f"no replica of {serve_label} served the "
                        f"request within {timeout}s"
                    )
                    exc.tried = list(tried)
                    raise exc
                key = state.table.pick(exclude)
                if key is None:
                    if exclude:
                        exclude = set()  # full rescan before backing off
                        continue
                    if timeout - remaining + backoff > timeout * 0.5:
                        # half the deadline burned with NOTHING routable:
                        # surface it as capacity, not a deadline miss
                        exc = Unavailable(
                            f"{serve_label}: no routable replica"
                        )
                        exc.tried = list(tried)
                        raise exc
                    time.sleep(min(backoff, remaining))
                    backoff = min(backoff * 2, 0.25)
                    continue
                server = lookup_replica(key)
                if server is None:
                    # an in-flight request just DISCOVERED the replica's
                    # registry entry is gone — count the removal (it was
                    # silent before) and route around it
                    state.table.release(key)
                    state.table.remove(key, "ejected")
                    if span is not None:
                        span.add_event("replica.vanished", {"replica": key})
                    exclude.add(key)
                    continue
                submit_t0 = time.perf_counter()
                try:
                    if self.metrics is not None:
                        self.metrics.observe(
                            "tfk8s_gateway_queue_seconds",
                            time.perf_counter() - t0,
                            {"serve": serve_label},
                        )
                    result = server.submit(
                        payload, timeout=remaining, traceparent=traceparent,
                        tenant=tenant, priority=priority,
                    )
                    state.table.report_outcome(
                        key, "ok", time.perf_counter() - submit_t0
                    )
                    return result
                except Draining:
                    # rolling out from under us — retry the next-least-
                    # loaded replica (the zero-failed-request contract)
                    self._count_retry(serve_label, tenant, "draining")
                    if span is not None:
                        span.add_event("retry", {
                            "reason": "Draining", "replica": key,
                            "backoff_s": 0.0,
                        })
                    exclude.add(key)
                    continue
                except DeadlineExceeded as exc:
                    # the deadline died ON this replica: feed the health
                    # machine (ratio-based eject) and surface it typed
                    state.table.report_outcome(key, "deadline")
                    tried.append(key)
                    exc.tried = list(tried)
                    raise
                except (ReplicaUnavailable, OSError) as exc:
                    # the replica died mid-flight (crash, wire cut,
                    # connection reset) — retriable on a survivor while
                    # the deadline, attempt cap, and budget allow
                    state.table.report_outcome(key, "transport_error")
                    tried.append(key)
                    exclude.add(key)
                    transport_retries += 1
                    if (transport_retries <= MAX_DISPATCH_RETRIES
                            and state.retry_budget.try_accept()):
                        self._count_retry(serve_label, tenant, "transport")
                        if span is not None:
                            span.add_event("retry", {
                                "reason": "ReplicaUnavailable",
                                "replica": key,
                            })
                        continue
                    wrapped = ReplicaUnavailable(
                        f"{serve_label}: replica {key} failed mid-flight "
                        f"({exc}) with the retry budget exhausted"
                    )
                    wrapped.tried = list(tried)
                    raise wrapped from exc
                finally:
                    state.table.release(key)
        finally:
            release()

    def _dispatch_disagg(self, state: _ServeState, namespace: str,
                         name: str, tenant: str, payload: Any,
                         timeout: float, session: Optional[str] = None,
                         meta: Optional[Dict[str, str]] = None) -> Any:
        """The disaggregated request path: (1) prefill on the affinity
        ring's owner of the prompt's page-aligned prefix digest (warm KV
        prefix reuse), (2) a gateway-brokered KV page handoff, (3)
        decode on the least-loaded decode replica. The gateway holds the
        buffer between phases, so a decode replica dying mid-transfer is
        absorbed by re-picking a survivor — the prefill work is never
        repeated for a decode-side failure."""
        serve_label = f"{namespace}/{name}"
        deadline = time.monotonic() + timeout
        t0 = time.perf_counter()
        tracer = get_tracer()
        span = tracer.current_span()
        traceparent = span.traceparent if span is not None else None
        priority = state.admission.priority_of(tenant)
        # the affinity key: an explicit session token wins (follow-up
        # turns keep their pin even as the shared history grows past the
        # first page); otherwise the page-aligned prefix digest of the
        # prompt itself (co-locates prompts sharing a system prefix)
        raw = payload.get("tokens") if isinstance(payload, dict) else payload
        try:
            toks = [int(t) for t in raw] if raw is not None else []
        except (TypeError, ValueError):
            toks = []
        # the digest key is ALWAYS the prompt's first-page digest (it is
        # what replicas report to the cache directory); the ring key may
        # be the caller's opaque session token instead
        dkey = affinity_key_of(toks, state.page_size) if toks else None
        akey: Optional[str] = (session or "").strip() or dkey
        if meta is not None and akey:
            meta["session"] = akey
        # cache directory (runtime/kvtier): a fresh report naming a
        # replica that HOLDS this prefix overrides the ring's guess; if
        # the pick still lands elsewhere, the owner rides along as a
        # peer-fetch hint so the prefill replica can pull the warm pages
        # instead of recomputing them
        kv_owner: Optional[str] = None
        if state.kv_dir is not None and dkey is not None:
            self._kv_directory_refresh(state)
            kv_owner, outcome = state.kv_dir.lookup(dkey)
            if self.metrics is not None:
                self.metrics.inc("tfk8s_gateway_kv_directory_total", 1.0, {
                    "serve": serve_label, "outcome": outcome,
                })
            if span is not None:
                span.add_event("kv_directory.lookup", {
                    "outcome": outcome, "owner": kv_owner or "",
                })
        owner = kv_owner
        release = state.admission.admit(
            tenant, state.prefill.least_depth(), state.queue_limit
        )
        try:
            prefill_res = self._run_phase(
                state, state.prefill, serve_label, tenant, deadline,
                timeout, t0, span, akey,
                lambda srv, rem, key: srv.submit_prefill(
                    payload, timeout=rem, traceparent=traceparent,
                    tenant=tenant, priority=priority,
                    # hint only when the pick LOST the directory owner
                    # (spill, owner in the decode pool, owner ejected):
                    # a replica never peer-fetches from itself
                    kv_peer=(owner if owner and owner != key else ""),
                ),
                preferred=kv_owner,
            )
            buf = prefill_res["handoff"]
            nbytes = 0
            outcome = "ok"
            ht0 = time.perf_counter()
            try:
                with tracer.start_span("handoff.transfer", attributes={
                    "serve": serve_label,
                    "pages": buf.n_pages,
                }) as hs:
                    buf, nbytes = self.transport.transfer(buf)
                    hs.set_attribute("bytes", nbytes)
            except HandoffError:
                outcome = "corrupt"
                raise
            finally:
                if self.metrics is not None:
                    self.metrics.inc(
                        "tfk8s_disagg_handoffs_total", 1.0,
                        {"serve": serve_label, "outcome": outcome},
                    )
                    self.metrics.observe(
                        "tfk8s_disagg_handoff_seconds",
                        time.perf_counter() - ht0, {"serve": serve_label},
                    )
                    if nbytes:
                        self.metrics.observe(
                            "tfk8s_disagg_handoff_bytes", float(nbytes),
                            {"serve": serve_label},
                        )
            return self._run_phase(
                state, state.decode, serve_label, tenant, deadline,
                timeout, None, span, None,
                lambda srv, rem, key: srv.submit_handoff(
                    buf, timeout=rem, traceparent=traceparent,
                    tenant=tenant, priority=priority,
                ),
            )
        finally:
            release()

    def _kv_directory_refresh(self, state: _ServeState) -> None:
        """Pull ``kv_digest_report`` from every routable replica of the
        serve (both phase pools — decode replicas hold imported prefixes
        too) into the cache directory. Rate-limited by the directory's
        own ``should_poll`` throttle (ttl/2), so the hot path amortizes
        the sweep; a replica that vanished or predates the report API
        simply drops out of the directory at its next TTL expiry."""
        kv_dir = state.kv_dir
        if kv_dir is None or not kv_dir.should_poll():
            return
        for _, table in state.named_tables():
            if table is None:
                continue
            for key, _depth in table.targets():
                server = lookup_replica(key)
                report_fn = getattr(server, "kv_digest_report", None)
                if report_fn is None:
                    kv_dir.forget(key)
                    continue
                try:
                    kv_dir.report(key, report_fn())
                except Exception:  # noqa: BLE001 - a dying replica's
                    # report must never fail the request being routed
                    kv_dir.forget(key)

    def _run_phase(self, state: _ServeState, table: RouteTable,
                   serve_label: str, tenant: str, deadline: float,
                   timeout: float, t0: Optional[float], span,
                   affinity_key: Optional[str], call,
                   preferred: Optional[str] = None) -> Any:
        """One phase of a disaggregated dispatch: the pick/submit/retry
        loop of :meth:`dispatch`, against ONE pool's route table.
        ``call(server, remaining, key)`` performs the phase's submit;
        the loop owns routing, outcome feedback, Draining/vanished/crash
        re-dispatch, and the typed surfacing contract. ``preferred`` is
        the cache directory's confirmed-warm replica, honored by the
        pick when routable; once excluded (drain, crash) the retry walk
        proceeds without it."""
        phase = table.phase or "serve"
        exclude: set = set()
        tried: list = []
        transport_retries = 0
        backoff = 0.005
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                exc = DeadlineExceeded(
                    f"no {phase} replica of {serve_label} served the "
                    f"request within {timeout}s"
                )
                exc.tried = list(tried)
                raise exc
            key = table.pick(exclude, affinity_key=affinity_key,
                             preferred=preferred)
            if key is None:
                if exclude:
                    exclude = set()  # full rescan before backing off
                    continue
                if timeout - remaining + backoff > timeout * 0.5:
                    exc = Unavailable(
                        f"{serve_label}: no routable {phase} replica"
                    )
                    exc.tried = list(tried)
                    raise exc
                time.sleep(min(backoff, remaining))
                backoff = min(backoff * 2, 0.25)
                continue
            server = lookup_replica(key)
            if server is None:
                table.release(key)
                table.remove(key, "ejected")
                if span is not None:
                    span.add_event("replica.vanished", {"replica": key})
                exclude.add(key)
                continue
            submit_t0 = time.perf_counter()
            try:
                if t0 is not None and self.metrics is not None:
                    # admission+routing delay lands once, on the phase
                    # requests enter first (prefill)
                    self.metrics.observe(
                        "tfk8s_gateway_queue_seconds",
                        time.perf_counter() - t0, {"serve": serve_label},
                    )
                    t0 = None
                result = call(server, remaining, key)
                table.report_outcome(
                    key, "ok", time.perf_counter() - submit_t0
                )
                return result
            except Draining:
                self._count_retry(serve_label, tenant, "draining")
                if span is not None:
                    span.add_event("retry", {
                        "reason": "Draining", "replica": key,
                        "phase": phase, "backoff_s": 0.0,
                    })
                exclude.add(key)
                continue
            except DeadlineExceeded as exc:
                table.report_outcome(key, "deadline")
                tried.append(key)
                exc.tried = list(tried)
                raise
            except (ReplicaUnavailable, OSError) as exc:
                # the phase target died mid-flight. For decode this is
                # the handoff-target-dies case: the gateway still holds
                # the buffer, so a survivor takes the SAME handoff
                table.report_outcome(key, "transport_error")
                tried.append(key)
                exclude.add(key)
                transport_retries += 1
                if (transport_retries <= MAX_DISPATCH_RETRIES
                        and state.retry_budget.try_accept()):
                    self._count_retry(serve_label, tenant, "transport")
                    if span is not None:
                        span.add_event("retry", {
                            "reason": "ReplicaUnavailable",
                            "replica": key, "phase": phase,
                        })
                    continue
                wrapped = ReplicaUnavailable(
                    f"{serve_label}: {phase} replica {key} failed "
                    f"mid-flight ({exc}) with the retry budget exhausted"
                )
                wrapped.tried = list(tried)
                raise wrapped from exc
            finally:
                table.release(key)

    # -- /debug/routes -------------------------------------------------------

    def debug_routes(self) -> Dict[str, Any]:
        """The ``/debug/routes`` zpage body: every serve's route table(s)
        — replica, health state, effective depth, in-flight — plus the
        affinity ring's ownership map where prefix routing is on."""
        with self._states_lock:
            items = list(self._states.items())
        serves: Dict[str, Any] = {}
        for (ns, name), st in items:
            entry: Dict[str, Any] = {}
            for phase, table in st.named_tables():
                block: Dict[str, Any] = {"replicas": table.debug_rows()}
                ring = table.ring_describe()
                if ring is not None:
                    block["ring"] = ring
                entry[phase or "default"] = block
            if st.kv_dir is not None:
                # the KV economy's routing view: per-replica digest
                # counts, host-tier occupancy, and lookup outcomes
                entry["kv_directory"] = st.kv_dir.describe()
            serves[f"{ns}/{name}"] = entry
        return {"serves": serves}
