"""Per-tenant admission control at the serving front door.

Each tenant (the request's ``X-Tenant`` header) gets its own
reservation-style token bucket (``client/ratelimit.py`` — the same
flowcontrol shape the clientset installs) sized by its
:class:`~tfk8s_tpu.api.types.TenantQuota`, an optional in-flight
concurrency cap, and a priority class. Admission is strictly
non-blocking: a request either enters now or is shed with a typed 429
carrying the exact Retry-After — the bucket's token-accrual debt for
quota sheds, a queue-pressure heuristic for priority sheds — so shed
traffic backs off instead of re-hammering.

Priority shedding ("a full queue sheds low priority first"): each
priority class tolerates a different queue occupancy on the LEAST
loaded replica before it is turned away — priority 0 sheds once the
queue is half full, 1 at three quarters, >= 2 only when the replica
itself would shed. As pressure rises, low-priority tenants lose
admission first and the headroom they vacate keeps high-priority
latency flat; no tenant can buy more than its bucket regardless of
priority, which is what stops one abusive tenant starving the rest.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from tfk8s_tpu.api.types import TenantPolicy, TenantQuota
from tfk8s_tpu.client.ratelimit import TokenBucketRateLimiter
from tfk8s_tpu.obs.trace import get_tracer
from tfk8s_tpu.runtime.server import Overloaded, QuotaExceeded
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("gateway.admission")


def shed_threshold(priority: int) -> float:
    """Queue-occupancy fraction at which a priority class is shed:
    0 -> 0.5, 1 -> 0.75, >= 2 -> 1.0 (only the replica's own bound)."""
    return min(1.0, 0.5 + 0.25 * max(priority, 0))


def _overload_retry_after(depth: float, limit: int) -> float:
    """Retry-After for a pressure shed: scaled with occupancy — a nearly
    full queue needs longer to drain below the caller's band than a
    half-full one. Heuristic by design (the true drain rate is the
    replicas' to know); 50-250 ms spans the batching executor's drain
    timescales at every benched rate."""
    frac = min(depth / limit, 1.0) if limit > 0 else 1.0
    return 0.05 + 0.2 * frac


class _TenantState:
    __slots__ = ("quota", "bucket", "inflight")

    def __init__(self, quota: TenantQuota):
        self.quota = quota
        # qps == 0 means unmetered rate (concurrency/priority still apply)
        self.bucket = (
            TokenBucketRateLimiter(quota.qps, quota.burst or 1)
            if quota.qps > 0 else None
        )
        self.inflight = 0


class TenantAdmission:
    """Admission state for ONE TPUServe: per-tenant buckets + in-flight
    counts, reconfigured in place when the spec's TenantPolicy changes
    (bucket state survives for tenants whose quota is unchanged — a
    policy edit must not hand every tenant a free full burst)."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._policy = TenantPolicy()
        self._states: Dict[str, _TenantState] = {}

    def configure(self, policy: TenantPolicy) -> None:
        with self._lock:
            if policy == self._policy:
                return
            for tenant, state in list(self._states.items()):
                want = self._quota_for_locked(policy, tenant)
                if want != state.quota:
                    fresh = _TenantState(want)
                    fresh.inflight = state.inflight  # in-flight survives
                    self._states[tenant] = fresh
            self._policy = policy
        log.info("admission policy updated: enabled=%s tenants=%d",
                 policy.enabled, len(policy.tenants))

    @staticmethod
    def _quota_for_locked(policy: TenantPolicy, tenant: str) -> TenantQuota:
        return policy.tenants.get(tenant, policy.default_quota)

    def _state(self, tenant: str) -> _TenantState:
        state = self._states.get(tenant)
        if state is None:
            state = _TenantState(self._quota_for_locked(self._policy, tenant))
            self._states[tenant] = state
        return state

    def admit(self, tenant: str, depth: float, limit: int) -> Callable[[], None]:
        """Admit ``tenant`` given the least-loaded replica's effective
        ``depth`` against its ``limit``, or raise the typed shed
        (Overloaded for pressure, QuotaExceeded for this tenant's own
        budget). Returns the release callable that ends the request's
        in-flight lease; callers MUST invoke it exactly once.

        The decision annotates the caller's ambient span (when one is
        open) with an ``admit``/``shed`` event — a shed request's trace
        shows exactly WHICH rule turned it away."""
        span = get_tracer().current_span()
        priority = 0
        # unmetered admission (policy disabled) is still an admission
        # decision — it gets the admit event, just no lease to release
        release: Callable[[], None] = self._release_noop
        try:
            with self._lock:
                if self._policy.enabled:
                    state = self._state(tenant)
                    quota = state.quota
                    priority = quota.priority
                    # pressure first (no side effects): the shed threshold
                    # for this tenant's priority class at the best replica
                    if limit > 0 and depth >= limit * shed_threshold(quota.priority):
                        exc = Overloaded(
                            int(depth) if depth != float("inf") else limit,
                            limit,
                            retry_after_s=_overload_retry_after(depth, limit),
                        )
                        exc.shed_reason = "priority"
                        raise exc
                    if state.bucket is not None:
                        delay = state.bucket.try_accept_or_delay()
                        if delay > 0:
                            raise QuotaExceeded(tenant, delay, reason="qps")
                    if quota.max_concurrency and state.inflight >= quota.max_concurrency:
                        raise QuotaExceeded(
                            tenant,
                            (1.0 / quota.qps) if quota.qps > 0 else 0.05,
                            reason="concurrency",
                        )
                    state.inflight += 1
                    release = lambda: self._release(tenant)  # noqa: E731
        except Overloaded as exc:
            if span is not None:
                span.add_event("shed", {
                    "tenant": tenant, "reason": "priority",
                    "priority": priority,
                    "queue_depth": exc.queue_depth,
                    "retry_after_s": exc.retry_after_s,
                })
            raise
        except QuotaExceeded as exc:
            if span is not None:
                span.add_event("shed", {
                    "tenant": tenant, "reason": exc.reason,
                    "priority": priority,
                    "retry_after_s": exc.retry_after_s,
                })
            raise
        if span is not None:
            span.add_event("admit", {
                "tenant": tenant, "priority": priority,
                "queue_depth": depth if depth != float("inf") else -1.0,
            })
        return release

    def priority_of(self, tenant: str) -> int:
        """The tenant's configured priority class (0 when unmetered)."""
        with self._lock:
            if not self._policy.enabled:
                return 0
            return self._quota_for_locked(self._policy, tenant).priority

    @staticmethod
    def _release_noop() -> None:
        return None

    def _release(self, tenant: str) -> None:
        with self._lock:
            state = self._states.get(tenant)
            if state is not None and state.inflight > 0:
                state.inflight -= 1

    def inflight(self, tenant: str) -> int:
        with self._lock:
            state = self._states.get(tenant)
            return state.inflight if state else 0
