"""Thin pipelined client for the gateway wire API.

One persistent keep-alive connection per calling thread (HTTP/1.1 —
requests pipeline back-to-back on a warm socket instead of paying a TCP
handshake each), the ``ServeError`` taxonomy re-materialized from the
typed wire envelopes, and the same shed discipline as the in-process
``ServeClient``: a 429 (``Overloaded`` or ``QuotaExceeded``) is retried
inside the caller's deadline after a jittered backoff seeded by the
server's ``Retry-After`` — shed traffic spreads out instead of
re-hammering the front door in lockstep.

The wire layer is hand-rolled over a raw socket rather than
``http.client``: the stdlib stack routes every response through the
email-package header parser (~100us/request) and ships the request as
two ``send()`` calls, which is most of the wire-vs-in-process QPS gap
at saturation. Here a request is ONE pre-built buffer and one
``sendall``, and the response parse is a few ``partition`` calls on a
buffered reader — the gateway always frames with Content-Length, and
anything that doesn't parse drops the connection and surfaces as a
connection error.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Any, Dict, Optional, Tuple
from urllib.parse import urlsplit

from tfk8s_tpu.client.store import NotFound, Unavailable
from tfk8s_tpu.obs.trace import get_tracer
from tfk8s_tpu.runtime.server import (
    DeadlineExceeded,
    InvalidRequest,
    Overloaded,
    QuotaExceeded,
    RequestFailed,
    jittered_backoff,
)
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("gateway.client")


def _parse_retry_after(value: Optional[str]) -> Optional[float]:
    # the gateway sends fractional seconds; tolerate anything numeric
    try:
        s = float(value)  # type: ignore[arg-type]
        return s if s > 0 else None
    except (TypeError, ValueError):
        return None


def _map_error(status: int, reason: str, message: str,
               details: Dict[str, Any],
               retry_after_s: Optional[float]) -> Exception:
    """Wire envelope -> the typed exception it left the gateway as."""
    if status == 429 and reason == "QuotaExceeded":
        return QuotaExceeded(
            str(details.get("tenant", "")),
            retry_after_s or float(details.get("retryAfterS", 0.05) or 0.05),
            reason=str(details.get("quota", "qps")),
        )
    if status == 429:
        return Overloaded(
            int(details.get("queueDepth", 0) or 0),
            int(details.get("queueLimit", 0) or 0),
            retry_after_s=retry_after_s,
        )
    if status == 400:
        return InvalidRequest(message)
    if status == 404:
        return NotFound(message)
    if status == 503:
        return Unavailable(message)
    if status == 504:
        return DeadlineExceeded(message)
    return RequestFailed(f"HTTP {status} {reason}: {message}")


class GatewayClient:
    """Client for one TPUServe through the gateway front door.

    ``request`` raises the same taxonomy as the in-process
    ``ServeClient.request`` (plus ``store.NotFound`` for an unknown
    serve), so call sites swap between the two transports unchanged.
    """

    OVERLOAD_BACKOFF_S = 0.05
    # bounded transport-failure retries (gateway unreachable, 503 with
    # nothing routable): the gateway already re-dispatches around a dead
    # replica internally, so the client's policy is a small, jittered
    # second chance — not an amplifier
    TRANSPORT_RETRIES = 2
    TRANSPORT_BACKOFF_S = 0.02

    def __init__(self, url: str, name: str, namespace: str = "default",
                 tenant: str = "", timeout_s: float = 30.0):
        sp = urlsplit(url)
        if not sp.hostname:
            raise InvalidRequest(f"gateway url missing host: {url!r}")
        self._host = sp.hostname
        self._port = sp.port or 80
        self._path = f"/v1/serve/{namespace}/{name}"
        self.tenant = tenant
        self._timeout = timeout_s
        # the invariant prefix of every request this client sends; only
        # Content-Length and the body differ between requests
        self._head = (
            f"POST {self._path} HTTP/1.1\r\n"
            f"Host: {self._host}:{self._port}\r\n"
            "Content-Type: application/json\r\n"
            + (f"X-Tenant: {tenant}\r\n" if tenant else "")
        ).encode("ascii")
        # the sticky routing token a disaggregated gateway returned on
        # the last 200 (``x-tfk8s-session``): echoed on every later
        # request so follow-up turns stay affine to the replica holding
        # the conversation's warm KV prefix. Single-pool gateways never
        # set it; ``reset_session()`` starts a fresh conversation.
        self.session: Optional[str] = None
        # one warm connection per thread: sockets are not safely shared
        # mid-request, and per-thread reuse is what keeps the wire path
        # pipelined under a threaded load generator
        self._local = threading.local()

    # -- connection management -----------------------------------------------

    def _conn(self) -> Tuple[socket.socket, Any]:
        sock = getattr(self._local, "sock", None)
        if sock is None:
            sock = socket.create_connection(
                (self._host, self._port), timeout=self._timeout
            )
            # the request goes out as one sendall, but keep Nagle off so
            # a retransmitted tail never waits on the peer's delayed ACK
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._local.sock = sock
            self._local.reader = sock.makefile("rb")
        return sock, self._local.reader

    def _drop_conn(self) -> None:
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            self._local.sock = None
            reader, self._local.reader = self._local.reader, None
            try:
                reader.close()
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        self._drop_conn()

    def reset_session(self) -> None:
        """Forget the sticky routing token (a new conversation)."""
        self.session = None

    # -- wire ----------------------------------------------------------------

    def _roundtrip(self, body: bytes,
                   traceparent: str = "") -> Tuple[int, Dict[str, str], bytes]:
        """One POST over the warm connection; a connection gone stale
        between requests (server restart, idle FIN) gets ONE fresh-socket
        retry — the request was never processed, so this is safe.

        ``traceparent`` (per-request — each attempt carries its own span
        context) rides as the W3C header between the invariant prefix and
        the framing."""
        tp = (
            f"traceparent: {traceparent}\r\n".encode("ascii")
            if traceparent else b""
        )
        session = self.session
        if session:
            tp += f"x-tfk8s-session: {session}\r\n".encode("ascii")
        request = b"%s%sContent-Length: %d\r\n\r\n%s" % (
            self._head, tp, len(body), body
        )
        for attempt in (0, 1):
            sock, reader = self._conn()
            try:
                sock.sendall(request)
                return self._read_response(reader)
            except OSError:
                self._drop_conn()
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def _read_response(self, reader: Any) -> Tuple[int, Dict[str, str], bytes]:
        """Parse one Content-Length-framed HTTP/1.1 response."""
        line = reader.readline(4096)
        if not line.startswith(b"HTTP/1."):
            # empty read = peer closed the idle connection; anything else
            # is a framing error — either way the socket is unusable
            raise ConnectionResetError(
                f"bad status line from gateway: {line[:80]!r}"
            )
        try:
            status = int(line.split(b" ", 2)[1])
        except (IndexError, ValueError):
            raise ConnectionResetError(f"bad status line: {line[:80]!r}")
        headers: Dict[str, str] = {}
        while True:
            line = reader.readline(4096)
            if line in (b"\r\n", b"\n"):
                break
            if not line:
                raise ConnectionResetError("connection closed mid-headers")
            name, _, value = line.partition(b":")
            headers[name.decode("latin-1").strip().lower()] = (
                value.decode("latin-1").strip()
            )
        try:
            n = int(headers.get("content-length", "0") or "0")
        except ValueError:
            # a garbled frame leaves unread body bytes on the socket —
            # surfacing it as a connection error makes _roundtrip DROP
            # the warm socket instead of handing the next request on
            # this thread the previous response's stale bytes
            raise ConnectionResetError(
                "bad Content-Length from gateway: "
                f"{headers.get('content-length')!r}"
            )
        data = reader.read(n) if n else b""
        if len(data) < n:
            raise ConnectionResetError("connection closed mid-body")
        if headers.get("connection", "").lower() == "close":
            self._drop_conn()
        return status, headers, data

    def request(self, payload: Any, timeout: float = 30.0) -> Any:
        """Submit one request through the gateway; retries shed (429)
        responses with jittered backoff inside ``timeout`` seconds.

        The whole exchange (every retry included) rides ONE
        ``gateway.client.request`` span whose context crosses the wire as
        the ``traceparent`` header — the server continues the trace, so
        client, gateway, and decode loop share one trace id. Retries
        annotate the span with typed ``retry`` events."""
        deadline = time.monotonic() + timeout
        shed_backoff = self.OVERLOAD_BACKOFF_S
        transport_backoff = self.TRANSPORT_BACKOFF_S
        transport_retries = 0
        attempt = 0
        with get_tracer().start_span(
            "gateway.client.request",
            attributes={"path": self._path, "tenant": self.tenant},
        ) as span:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise DeadlineExceeded(
                        f"gateway request deadline ({timeout}s) exceeded"
                    )
                body = json.dumps(
                    {"payload": payload, "timeoutS": remaining}
                ).encode()
                attempt += 1
                try:
                    status, headers, data = self._roundtrip(
                        body, traceparent=span.traceparent
                    )
                except OSError as exc:
                    if transport_retries < self.TRANSPORT_RETRIES:
                        transport_retries += 1
                        delay = jittered_backoff(None, transport_backoff)
                        if delay < deadline - time.monotonic():
                            span.add_event("retry", {
                                "attempt": attempt,
                                "reason": "transport",
                                "backoff_s": delay,
                            })
                            time.sleep(delay)
                            transport_backoff = min(transport_backoff * 2, 0.5)
                            continue
                    raise Unavailable(f"gateway unreachable: {exc}") from exc
                if status == 200:
                    sess = headers.get("x-tfk8s-session")
                    if sess:
                        self.session = sess
                    span.set_attribute("http.status_code", 200)
                    return json.loads(data)["result"]
                try:
                    envelope = json.loads(data)
                except ValueError:
                    envelope = {}
                err = _map_error(
                    status,
                    str(envelope.get("reason", "")),
                    str(envelope.get("message", data[:200])),
                    envelope.get("details") or {},
                    _parse_retry_after(
                        {k.lower(): v for k, v in headers.items()}.get("retry-after")
                    ),
                )
                if isinstance(err, (Overloaded, QuotaExceeded)):
                    delay = jittered_backoff(err.retry_after_s, shed_backoff)
                    if delay < deadline - time.monotonic():
                        span.add_event("retry", {
                            "attempt": attempt,
                            "reason": type(err).__name__,
                            "status": status,
                            "backoff_s": delay,
                        })
                        time.sleep(delay)
                        shed_backoff = min(shed_backoff * 2, 1.0)
                        continue
                elif (isinstance(err, Unavailable)
                        and transport_retries < self.TRANSPORT_RETRIES):
                    # 503: a replica died mid-flight with the gateway's
                    # retry budget drained, or nothing was routable —
                    # both transient while the controller replaces the
                    # replica, so give it the same bounded second chance
                    transport_retries += 1
                    delay = jittered_backoff(None, transport_backoff)
                    if delay < deadline - time.monotonic():
                        span.add_event("retry", {
                            "attempt": attempt,
                            "reason": "Unavailable",
                            "status": status,
                            "backoff_s": delay,
                        })
                        time.sleep(delay)
                        transport_backoff = min(transport_backoff * 2, 0.5)
                        continue
                span.set_attribute("http.status_code", status)
                raise err
