"""Least-queue-depth routing for the serving front door.

Replaces the client-side round-robin-over-Ready with load-aware
selection fed by the signal the replicas ALREADY publish: each model
server reports ``serving_queue_depth`` through ``runtime/progress.py``
→ kubelet flush → ``pod.status.training`` — the channel the autoscaler
consumes. The route table EMA-smooths the per-replica depth with the
autoscaler's own alpha (one smoothing constant, two consumers — the two
views of "how loaded is this replica" can never disagree on dynamics)
and corrects for publication lag by adding the requests IT has in
flight to each replica (least-outstanding-requests on top of the
published base, so a burst between kubelet flushes doesn't pile onto
the momentarily-least-loaded replica).

Replica lifecycle in the table:

- **discovery**: a clientset pod list (label selector, TTL-cached like
  ``ServeClient``) admits Ready replicas and refreshes their depth;
- **stale aging**: an entry not re-observed within ``stale_after_s``
  (vanished pod, wedged kubelet) silently leaves the routing set;
- **draining**: ``mark_draining`` removes a replica the instant its
  drain starts — the gateway subscribes to
  ``runtime.server.add_drain_hook``, which fires when the replica
  unregisters, BEFORE the kubelet would publish anything — preserving
  the zero-failed-request rollout contract on the wire path.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from tfk8s_tpu.obs.trace import get_tracer
from tfk8s_tpu.trainer.serve_controller import EMA_ALPHA
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("gateway.router")

# an entry not re-observed within this window is presumed vanished
STALE_AFTER_S = 3.0
# discovery refresh cadence (matches ServeClient's endpoint cache TTL)
CACHE_TTL_S = 0.25


class _Entry:
    __slots__ = ("depth", "seen")

    def __init__(self, depth: float, seen: float):
        self.depth = depth
        self.seen = seen


class RouteTable:
    """Load-aware route table for ONE TPUServe. ``pick`` returns the
    least-loaded routable replica key and leases an in-flight slot on
    it; ``release`` returns the slot when the dispatch finishes either
    way. ``clientset=None`` disables discovery — unit tests (and any
    out-of-band feed) drive the table through ``observe`` directly."""

    def __init__(
        self,
        clientset=None,
        name: str = "",
        namespace: str = "default",
        cache_ttl_s: float = CACHE_TTL_S,
        stale_after_s: float = STALE_AFTER_S,
        metrics=None,
        clock=time.monotonic,
    ):
        self._cs = clientset
        self.name = name
        self.namespace = namespace
        self._cache_ttl = cache_ttl_s
        self._stale_after = stale_after_s
        self._metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self._inflight: Dict[str, int] = {}
        # key -> when the drain was observed (purged once stale: by then
        # the pod is gone from every discovery source)
        self._draining: Dict[str, float] = {}
        self._last_refresh = 0.0

    # -- feeds ---------------------------------------------------------------

    def observe(self, key: str, depth: float) -> None:
        """Fold one published depth sample into the table (EMA-smoothed,
        the autoscaler's alpha)."""
        now = self._clock()
        with self._lock:
            if key in self._draining:
                return
            e = self._entries.get(key)
            if e is None:
                self._entries[key] = _Entry(float(depth), now)
            else:
                e.depth = EMA_ALPHA * float(depth) + (1 - EMA_ALPHA) * e.depth
                e.seen = now

    def mark_draining(self, key: str) -> None:
        """Remove a replica from the routing set at drain START (the
        in-process drain hook) — requests already dispatched to it finish
        (the replica drains its queue); nothing new routes to it."""
        now = self._clock()
        with self._lock:
            if key not in self._entries and key not in self._draining:
                return
            self._entries.pop(key, None)
            self._draining[key] = now
        log.debug("%s/%s: %s draining; removed from route table",
                  self.namespace, self.name, key)

    def refresh(self, force: bool = False) -> None:
        """Re-discover Ready replicas and their published depths through
        the clientset (no-op within the TTL, or with no clientset)."""
        if self._cs is None:
            return
        now = self._clock()
        with self._lock:
            if not force and now - self._last_refresh < self._cache_ttl:
                return
            self._last_refresh = now
        # the list (rate-limited client call) runs OUTSIDE the table lock
        from tfk8s_tpu.runtime.server import replica_is_ready
        from tfk8s_tpu.trainer import labels as L

        pods, _rv = self._cs.pods(self.namespace).list(
            label_selector=L.serve_selector(self.name)
        )
        for p in pods:
            if replica_is_ready(p):
                self.observe(
                    p.metadata.key,
                    float(p.status.training.get("serving_queue_depth", 0.0)),
                )
        self._publish_gauges()

    # -- routing -------------------------------------------------------------

    def pick(self, exclude: Optional[Set[str]] = None) -> Optional[str]:
        """Least effective depth (published EMA + local in-flight) among
        fresh, non-draining, non-excluded replicas; leases an in-flight
        slot on the winner. None when nothing is routable."""
        self.refresh()
        now = self._clock()
        with self._lock:
            self._purge_locked(now)
            best: Optional[str] = None
            best_depth = 0.0
            for key in sorted(self._entries):  # sorted: deterministic ties
                if exclude and key in exclude:
                    continue
                d = self._entries[key].depth + self._inflight.get(key, 0)
                if best is None or d < best_depth:
                    best, best_depth = key, d
            if best is not None:
                self._inflight[best] = self._inflight.get(best, 0) + 1
        if best is not None:
            span = get_tracer().current_span()
            if span is not None:
                span.add_event("route.pick", {
                    "replica": best, "effective_depth": best_depth,
                })
        return best

    def release(self, key: str) -> None:
        with self._lock:
            n = self._inflight.get(key, 0)
            if n <= 1:
                self._inflight.pop(key, None)
            else:
                self._inflight[key] = n - 1

    def least_depth(self) -> float:
        """The least effective depth across routable replicas (inf when
        none) — the admission layer's cluster-pressure signal."""
        self.refresh()
        now = self._clock()
        with self._lock:
            self._purge_locked(now)
            depths = [
                e.depth + self._inflight.get(k, 0)
                for k, e in self._entries.items()
            ]
        return min(depths) if depths else float("inf")

    def targets(self) -> List[Tuple[str, float]]:
        """Routable (key, effective depth) pairs — debug/test surface."""
        now = self._clock()
        with self._lock:
            self._purge_locked(now)
            return sorted(
                (k, e.depth + self._inflight.get(k, 0))
                for k, e in self._entries.items()
            )

    # -- internals -----------------------------------------------------------

    def _purge_locked(self, now: float) -> None:
        for key, e in list(self._entries.items()):
            if now - e.seen > self._stale_after:
                del self._entries[key]
                log.debug("%s/%s: %s aged out of route table",
                          self.namespace, self.name, key)
        for key, when in list(self._draining.items()):
            if now - when > self._stale_after:
                del self._draining[key]

    def _publish_gauges(self) -> None:
        if self._metrics is None:
            return
        rows = self.targets()  # takes the lock itself; gauges set outside
        labels = {"serve": f"{self.namespace}/{self.name}"}
        self._metrics.set_gauge(
            "tfk8s_gateway_route_replicas", float(len(rows)), labels
        )
        self._metrics.set_gauge(
            "tfk8s_gateway_route_depth",
            min((d for _, d in rows), default=0.0), labels,
        )
