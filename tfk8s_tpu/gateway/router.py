"""Least-queue-depth routing for the serving front door.

Replaces the client-side round-robin-over-Ready with load-aware
selection fed by the signal the replicas ALREADY publish: each model
server reports ``serving_queue_depth`` through ``runtime/progress.py``
→ kubelet flush → ``pod.status.training`` — the channel the autoscaler
consumes. The route table EMA-smooths the per-replica depth with the
autoscaler's own alpha (one smoothing constant, two consumers — the two
views of "how loaded is this replica" can never disagree on dynamics)
and corrects for publication lag by adding the requests IT has in
flight to each replica (least-outstanding-requests on top of the
published base, so a burst between kubelet flushes doesn't pile onto
the momentarily-least-loaded replica).

Replica lifecycle in the table:

- **discovery**: a clientset pod list (label selector, TTL-cached like
  ``ServeClient``) admits Ready replicas and refreshes their depth;
- **stale aging**: an entry not re-observed within ``stale_after_s``
  (vanished pod, wedged kubelet) silently leaves the routing set;
- **draining**: ``mark_draining`` removes a replica the instant its
  drain starts — the gateway subscribes to
  ``runtime.server.add_drain_hook``, which fires when the replica
  unregisters, BEFORE the kubelet would publish anything — preserving
  the zero-failed-request rollout contract on the wire path;
- **health ejection** (ISSUE 13, gateway/health.py): the dispatch loop
  feeds per-replica outcomes back through ``report_outcome`` — Healthy
  → Suspect → Ejected → half-open probe re-admit, driven by consecutive
  transport errors, the deadline-exceeded ratio, and the gray-failure
  latency detector. An UNPLANNED failure (crash, wire cut, slow box) is
  therefore discovered actively, well before passive stale aging; the
  availability floor degrades the last routable replica to
  Suspect-with-traffic instead of ejecting it.

Every removal — stale-aged, drain-purged, or discovered vanished by an
in-flight request — is counted in
``tfk8s_gateway_replica_removed_total{reason=stale|drained|ejected}``;
ejections in ``tfk8s_gateway_ejections_total{reason}``.
"""

from __future__ import annotations

import statistics
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from tfk8s_tpu.gateway import health as _health
from tfk8s_tpu.gateway.affinity import (
    AFFINITY_SPILL_DEPTH,
    DIRECTORY_SPILL_DEPTH,
    AffinityRing,
)
from tfk8s_tpu.obs.trace import get_tracer
from tfk8s_tpu.trainer.serve_controller import EMA_ALPHA
from tfk8s_tpu.utils.logging import get_logger

log = get_logger("gateway.router")

# an entry not re-observed within this window is presumed vanished
STALE_AFTER_S = 3.0
# discovery refresh cadence (matches ServeClient's endpoint cache TTL)
CACHE_TTL_S = 0.25


class _Entry:
    __slots__ = ("depth", "seen", "health")

    def __init__(self, depth: float, seen: float):
        self.depth = depth
        self.seen = seen
        self.health = _health.ReplicaHealth()


class RouteTable:
    """Load-aware route table for ONE TPUServe. ``pick`` returns the
    least-loaded routable replica key and leases an in-flight slot on
    it; ``release`` returns the slot when the dispatch finishes either
    way. ``clientset=None`` disables discovery — unit tests (and any
    out-of-band feed) drive the table through ``observe`` directly."""

    def __init__(
        self,
        clientset=None,
        name: str = "",
        namespace: str = "default",
        cache_ttl_s: float = CACHE_TTL_S,
        stale_after_s: float = STALE_AFTER_S,
        metrics=None,
        clock=time.monotonic,
        phase: str = "",
        affinity: bool = False,
    ):
        self._cs = clientset
        self.name = name
        self.namespace = namespace
        # disaggregated serves run one table per phase pool; discovery
        # then selects on the pool's phase label so prefill traffic can
        # never land on a decode replica (and vice versa)
        self.phase = phase
        # prefix-affinity: membership mirrors the entry set (added on
        # first observe, dropped with every removal), so ring state needs
        # no separate lifecycle. Guarded by self._lock like everything
        # else — AffinityRing itself is not thread-safe.
        self._ring: Optional[AffinityRing] = AffinityRing() if affinity else None
        self._cache_ttl = cache_ttl_s
        self._stale_after = stale_after_s
        self._metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self._inflight: Dict[str, int] = {}
        # key -> when the drain was observed (purged once stale: by then
        # the pod is gone from every discovery source)
        self._draining: Dict[str, float] = {}
        self._last_refresh = 0.0
        # key -> clock stamp of the last pick (kept past removal: the
        # chaos bench reads kill->last-routed as ejection_time_ms)
        self._last_pick: Dict[str, float] = {}

    # -- feeds ---------------------------------------------------------------

    def observe(self, key: str, depth: float) -> None:
        """Fold one published depth sample into the table (EMA-smoothed,
        the autoscaler's alpha)."""
        now = self._clock()
        with self._lock:
            if key in self._draining:
                return
            e = self._entries.get(key)
            if e is None:
                self._entries[key] = _Entry(float(depth), now)
                if self._ring is not None:
                    self._ring.add(key)
            else:
                e.depth = EMA_ALPHA * float(depth) + (1 - EMA_ALPHA) * e.depth
                e.seen = now

    def mark_draining(self, key: str) -> None:
        """Remove a replica from the routing set at drain START (the
        in-process drain hook) — requests already dispatched to it finish
        (the replica drains its queue); nothing new routes to it."""
        now = self._clock()
        with self._lock:
            if key not in self._entries and key not in self._draining:
                return
            self._removed_locked(key, "drained")
            self._draining[key] = now

    def remove(self, key: str, reason: str = "ejected") -> None:
        """Drop a replica an in-flight request DISCOVERED gone (its
        registry entry vanished mid-dispatch) — counted in the removal
        metric so a vanished replica is visible without a debugger."""
        with self._lock:
            if key not in self._entries:
                return
            self._removed_locked(key, reason)

    def refresh(self, force: bool = False) -> None:
        """Re-discover Ready replicas and their published depths through
        the clientset (no-op within the TTL, or with no clientset)."""
        if self._cs is None:
            return
        now = self._clock()
        with self._lock:
            if not force and now - self._last_refresh < self._cache_ttl:
                return
            self._last_refresh = now
        # the list (rate-limited client call) runs OUTSIDE the table lock
        from tfk8s_tpu.runtime.server import replica_is_ready
        from tfk8s_tpu.trainer import labels as L

        selector = (
            L.serve_phase_selector(self.name, self.phase)
            if self.phase else L.serve_selector(self.name)
        )
        pods, _rv = self._cs.pods(self.namespace).list(label_selector=selector)
        for p in pods:
            if replica_is_ready(p):
                self.observe(
                    p.metadata.key,
                    float(p.status.training.get("serving_queue_depth", 0.0)),
                )
        self._publish_gauges()

    # -- routing -------------------------------------------------------------

    def pick(
        self,
        exclude: Optional[Set[str]] = None,
        affinity_key: Optional[str] = None,
        preferred: Optional[str] = None,
    ) -> Optional[str]:
        """Least effective depth (published EMA + local in-flight +
        Suspect penalty) among fresh, non-draining, non-excluded,
        ROUTABLE replicas; leases an in-flight slot on the winner. An
        Ejected replica is routable only as a half-open probe (cooldown
        elapsed, probe circuit open) — the pick leases its probe slot.
        None when nothing is routable.

        With ``affinity_key`` (and the ring enabled), the consistent-hash
        owner of the key wins INSTEAD of the least-loaded replica —
        unless the owner is non-routable (ejected/draining replicas fall
        off the ring walk and their keys rebalance to the successor) or
        more than ``AFFINITY_SPILL_DEPTH`` effective requests deeper than
        the fleet minimum, in which case the request spills to the
        least-depth pick (warm KV is worth a bounded queue, not an
        unbounded one).

        ``preferred`` is the cache directory's answer (runtime/kvtier):
        a replica CONFIRMED to hold the prompt's prefix warm. It
        outranks the ring's guess — route ``directory`` — under its own
        slightly looser bound (``DIRECTORY_SPILL_DEPTH``); a
        non-routable or overloaded preferred replica falls back to the
        normal ring walk, costing at most a fallback prefill."""
        self.refresh()
        now = self._clock()
        probe = False
        route: Optional[str] = None
        with self._lock:
            self._purge_locked(now)

            def eff(key: str) -> float:
                e = self._entries[key]
                return (
                    e.depth + self._inflight.get(key, 0)
                    + e.health.depth_penalty()
                )

            best: Optional[str] = None
            best_depth = 0.0
            for key in sorted(self._entries):  # sorted: deterministic ties
                if exclude and key in exclude:
                    continue
                if not self._entries[key].health.routable(now):
                    continue
                d = eff(key)
                if best is None or d < best_depth:
                    best, best_depth = key, d
            if self._ring is not None:
                route = "none"
                if affinity_key:
                    route = "spill"
                    if preferred is not None and not (
                        exclude and preferred in exclude
                    ):
                        e = self._entries.get(preferred)
                        if e is not None and e.health.routable(now):
                            d = eff(preferred)
                            if best is None or (
                                d <= best_depth + DIRECTORY_SPILL_DEPTH
                            ):
                                best, best_depth = preferred, d
                                route = "directory"
                    if route != "directory":
                        for cand in self._ring.candidates(affinity_key):
                            if exclude and cand in exclude:
                                continue
                            e = self._entries.get(cand)
                            if e is None or not e.health.routable(now):
                                continue
                            d = eff(cand)
                            if best is None or (
                                d <= best_depth + AFFINITY_SPILL_DEPTH
                            ):
                                best, best_depth = cand, d
                                route = "affine"
                            # first ROUTABLE successor decides: pin/spill
                            break
            if best is not None:
                h = self._entries[best].health
                if h.state == _health.EJECTED:
                    probe = True
                    h.probe_inflight += 1
                self._inflight[best] = self._inflight.get(best, 0) + 1
                self._last_pick[best] = now
        if best is not None:
            if route is not None and self._metrics is not None:
                self._metrics.inc(
                    "tfk8s_gateway_affinity_requests_total", 1.0,
                    {"serve": f"{self.namespace}/{self.name}", "route": route,
                     **({"phase": self.phase} if self.phase else {})},
                )
            span = get_tracer().current_span()
            if span is not None:
                span.add_event("route.pick", {
                    "replica": best, "effective_depth": best_depth,
                    **({"probe": True} if probe else {}),
                    **({"route": route} if route is not None else {}),
                })
        return best

    def release(self, key: str) -> None:
        with self._lock:
            n = self._inflight.get(key, 0)
            if n <= 1:
                self._inflight.pop(key, None)
            else:
                self._inflight[key] = n - 1
            e = self._entries.get(key)
            if e is not None and e.health.probe_inflight > 0:
                e.health.probe_inflight -= 1  # half-open probe slot back

    def report_outcome(self, key: str, outcome: str,
                       latency_s: Optional[float] = None) -> None:
        """Dispatch feedback driving the health state machine. One call
        per dispatched attempt: ``outcome`` is ``"ok"`` (with the
        replica-observed latency), ``"transport_error"`` (connection
        failed / replica vanished / crashed mid-flight) or
        ``"deadline"`` (the caller's deadline died on this replica).
        Ejections honor the availability floor: the last routable
        replica degrades to Suspect-with-traffic instead."""
        now = self._clock()
        reason: Optional[str] = None
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return
            h = e.health
            if outcome == "ok":
                was_ejected = h.state == _health.EJECTED
                h.note_ok(latency_s, EMA_ALPHA)
                if was_ejected:
                    log.info("%s/%s: probe of %s succeeded; re-admitted",
                             self.namespace, self.name, key)
                elif (
                    _health.is_gray(h, self._fleet_median_locked(key))
                    and self._floor_allows_locked(key)
                ):
                    h.eject(now)
                    reason = "gray"
            else:
                verdict = (
                    h.note_transport_error()
                    if outcome == "transport_error" else h.note_deadline()
                )
                if verdict == "suspect":
                    h.state = _health.SUSPECT
                elif verdict == "eject":
                    if self._floor_allows_locked(key):
                        h.eject(now)
                        reason = (
                            "errors" if outcome == "transport_error"
                            else "deadline"
                        )
                    else:
                        # availability floor: never eject the last
                        # routable replica — degraded but serving beats
                        # nothing routable at all
                        h.state = _health.SUSPECT
                elif verdict == "reeject":
                    h.eject(now, escalate=True)
                    reason = "probe"
        if reason is not None:
            if self._metrics is not None:
                self._metrics.inc(
                    "tfk8s_gateway_ejections_total", 1.0,
                    {"serve": f"{self.namespace}/{self.name}",
                     "reason": reason},
                )
            span = get_tracer().current_span()
            if span is not None:
                span.add_event("replica.eject", {
                    "replica": key, "reason": reason,
                })
            log.warning("%s/%s: ejected %s (%s)",
                        self.namespace, self.name, key, reason)

    def least_depth(self) -> float:
        """The least effective depth across routable replicas (inf when
        none) — the admission layer's cluster-pressure signal."""
        self.refresh()
        now = self._clock()
        with self._lock:
            self._purge_locked(now)
            depths = [
                e.depth + self._inflight.get(k, 0)
                for k, e in self._entries.items()
                if e.health.state != _health.EJECTED
            ]
        return min(depths) if depths else float("inf")

    def targets(self) -> List[Tuple[str, float]]:
        """Routable (key, effective depth) pairs — debug/test surface
        and the gauge feed. Ejected replicas are out of the routing set
        (half-open probes aside) and don't list."""
        now = self._clock()
        with self._lock:
            self._purge_locked(now)
            return sorted(
                (k, e.depth + self._inflight.get(k, 0))
                for k, e in self._entries.items()
                if e.health.state != _health.EJECTED
            )

    def health_state(self, key: str) -> Optional[str]:
        """The replica's health state (health.HEALTHY/SUSPECT/EJECTED),
        or None when it left the table."""
        with self._lock:
            e = self._entries.get(key)
            return e.health.state if e is not None else None

    def debug_rows(self) -> List[dict]:
        """Full per-replica table dump for ``/debug/routes`` — unlike
        ``targets`` this includes Ejected entries (the interesting ones
        when debugging routing), with health state and in-flight count."""
        now = self._clock()
        with self._lock:
            self._purge_locked(now)
            return [
                {
                    "replica": k,
                    "health": e.health.state,
                    "effective_depth": round(
                        e.depth + self._inflight.get(k, 0)
                        + e.health.depth_penalty(), 3
                    ),
                    "inflight": self._inflight.get(k, 0),
                }
                for k, e in sorted(self._entries.items())
            ]

    def ring_describe(self) -> Optional[dict]:
        """The affinity ring's ownership map (None when affinity is
        off) — the ``/debug/routes`` companion to ``debug_rows``."""
        if self._ring is None:
            return None
        with self._lock:
            return self._ring.describe()

    def last_pick_s(self, key: str) -> Optional[float]:
        """Clock stamp of the LAST pick of ``key`` (kept past removal):
        kill-to-last-pick is the chaos bench's ``ejection_time_ms``."""
        with self._lock:
            return self._last_pick.get(key)

    # -- internals -----------------------------------------------------------

    def _floor_allows_locked(self, key: str) -> bool:
        """Availability floor: ejecting ``key`` must leave at least one
        routable (non-Ejected) replica."""
        return any(
            k != key and e.health.state != _health.EJECTED
            for k, e in self._entries.items()
        )

    def _fleet_median_locked(self, key: str) -> float:
        """Median latency EWMA of ``key``'s PEERS (non-ejected, with
        data) — excluding the candidate so one slow replica can't drag
        the gray-detection reference toward itself."""
        peers = [
            e.health.latency_ewma
            for k, e in self._entries.items()
            if k != key and e.health.latency_ewma is not None
            and e.health.state != _health.EJECTED
        ]
        return statistics.median(peers) if peers else 0.0

    def _removed_locked(self, key: str, reason: str) -> None:
        self._entries.pop(key, None)
        if self._ring is not None:
            self._ring.remove(key)
        if self._metrics is not None:
            self._metrics.inc(
                "tfk8s_gateway_replica_removed_total", 1.0,
                {"serve": f"{self.namespace}/{self.name}", "reason": reason},
            )
        log.debug("%s/%s: %s removed from route table (%s)",
                  self.namespace, self.name, key, reason)

    def _purge_locked(self, now: float) -> None:
        for key, e in list(self._entries.items()):
            if now - e.seen > self._stale_after:
                self._removed_locked(key, "stale")
        for key, when in list(self._draining.items()):
            if now - when > self._stale_after:
                del self._draining[key]

    def _publish_gauges(self) -> None:
        if self._metrics is None:
            return
        rows = self.targets()  # takes the lock itself; gauges set outside
        labels = {"serve": f"{self.namespace}/{self.name}"}
        if self.phase:
            labels["phase"] = self.phase
        self._metrics.set_gauge(
            "tfk8s_gateway_route_replicas", float(len(rows)), labels
        )
        self._metrics.set_gauge(
            "tfk8s_gateway_route_depth",
            min((d for _, d in rows), default=0.0), labels,
        )
        if self._ring is not None:
            with self._lock:
                members = len(self._ring)
            self._metrics.set_gauge(
                "tfk8s_gateway_affinity_ring_members", float(members), labels
            )
