"""Per-replica health state machine for the gateway's failure domains.

The RouteTable learns about replica failure three ways, all from stats
the dispatch loop already observes — no new probes, no sidecars:

- **consecutive transport errors** (connection refused/reset, the
  replica registry entry gone, ``ReplicaUnavailable`` off a crashed
  replica): one error makes the replica *Suspect* (still routable, but
  deprioritized), ``EJECT_AFTER_ERRORS`` in a row *Ejects* it;
- **deadline-exceeded ratio**: a replica that keeps burning callers'
  deadlines is failing even though its transport looks fine — past
  ``EJECT_DEADLINE_RATIO`` over a sliding window it ejects;
- **gray failure**: alive, correct, SLOW. A replica whose latency EWMA
  stands ``GRAY_FACTOR`` above the fleet median (minimum sample count,
  absolute floor) is ejected *before* it times callers out.

Ejected is not forever: after a cooldown the replica is **half-open** —
the circuit breaker admits at most ``PROBE_MAX_INFLIGHT`` concurrent
probe requests. A probe success closes the circuit (Healthy, cooldown
reset); a probe failure re-ejects with the cooldown doubled (capped).
This is also the re-admission path for a REPLACED replica: the serve
controller recreates a crashed pod under the same key, and the first
successful probe folds it back into the routing set.

The fleet-level decisions — the availability floor (never eject below
one routable replica) and the gray-detection median — live in
``RouteTable``, which owns the peer set. This module is the pure,
per-replica half: no clocks of its own (callers pass ``now``), no
locks, unit-testable in microseconds.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Optional

#: state names (also the ``state`` label on ejection metrics/traces)
HEALTHY = "healthy"
SUSPECT = "suspect"
EJECTED = "ejected"

#: consecutive transport errors before a replica turns Suspect / Ejected
SUSPECT_AFTER_ERRORS = 1
EJECT_AFTER_ERRORS = 3
#: sliding outcome window for the deadline-ratio detector
DEADLINE_WINDOW = 16
DEADLINE_MIN_SAMPLES = 8
EJECT_DEADLINE_RATIO = 0.5
#: gray detector: EWMA >= GRAY_FACTOR x fleet median, with guards so
#: microsecond jitter on an idle fleet can't eject anyone
GRAY_FACTOR = 3.0
GRAY_MIN_SAMPLES = 8
GRAY_FLOOR_S = 0.02
#: half-open probe schedule: first re-admission attempt after
#: EJECT_COOLDOWN_S; each failed probe doubles it up to the cap
EJECT_COOLDOWN_S = 0.5
EJECT_COOLDOWN_MAX_S = 5.0
#: circuit breaker: concurrent requests allowed into an Ejected replica
PROBE_MAX_INFLIGHT = 1
#: effective-depth penalty a Suspect replica carries in pick(). It must
#: DEPRIORITIZE, not starve: a Suspect that never gets picked again can
#: neither accumulate the consecutive errors that eject it nor the ok
#: that clears it — a corpse would hide in Suspect forever. Half an
#: in-flight request keeps it behind healthy peers at equal load while
#: routine load fluctuation still sends it the occasional verdict
#: request.
SUSPECT_DEPTH_PENALTY = 0.5


class ReplicaHealth:
    """Per-replica health bookkeeping (one per RouteTable entry)."""

    __slots__ = (
        "state", "consec_errors", "latency_ewma", "samples", "window",
        "ejected_at", "cooldown_s", "probe_inflight", "ejections",
    )

    def __init__(self) -> None:
        self.state = HEALTHY
        self.consec_errors = 0
        self.latency_ewma: Optional[float] = None
        self.samples = 0
        # 1 = deadline-exceeded outcome, 0 = anything else
        self.window: Deque[int] = deque(maxlen=DEADLINE_WINDOW)
        self.ejected_at = 0.0
        self.cooldown_s = EJECT_COOLDOWN_S
        self.probe_inflight = 0
        self.ejections = 0

    # -- outcome folding -----------------------------------------------------

    def note_ok(self, latency_s: Optional[float], alpha: float) -> None:
        """A served request: reset the failure counters, fold the
        latency EWMA. A Suspect recovers; an Ejected replica's
        successful half-open probe closes the circuit (cooldown
        reset)."""
        self.consec_errors = 0
        self.window.append(0)
        if latency_s is not None:
            self.samples += 1
            self.latency_ewma = (
                latency_s if self.latency_ewma is None
                else alpha * latency_s + (1 - alpha) * self.latency_ewma
            )
        if self.state == EJECTED:
            self.cooldown_s = EJECT_COOLDOWN_S
        self.state = HEALTHY

    def note_transport_error(self) -> Optional[str]:
        """A transport-class failure. Returns the transition the caller
        should apply (subject to its availability floor): ``"eject"``,
        ``"suspect"``, or ``"reeject"`` (a failed half-open probe —
        escalate the cooldown)."""
        self.consec_errors += 1
        self.window.append(0)
        if self.state == EJECTED:
            return "reeject"
        if self.consec_errors >= EJECT_AFTER_ERRORS:
            return "eject"
        if self.consec_errors >= SUSPECT_AFTER_ERRORS:
            return "suspect"
        return None

    def note_deadline(self) -> Optional[str]:
        """The caller's deadline died on this replica. One deadline makes
        it Suspect; a window past ``EJECT_DEADLINE_RATIO`` ejects."""
        self.window.append(1)
        if self.state == EJECTED:
            return "reeject"
        if (
            len(self.window) >= DEADLINE_MIN_SAMPLES
            and sum(self.window) / len(self.window) >= EJECT_DEADLINE_RATIO
        ):
            self.window.clear()
            return "eject"
        return "suspect"

    # -- transitions ---------------------------------------------------------

    def eject(self, now: float, escalate: bool = False) -> None:
        """Open the circuit. ``escalate`` (failed probe) doubles the
        cooldown up to the cap instead of starting fresh."""
        if escalate:
            self.cooldown_s = min(self.cooldown_s * 2, EJECT_COOLDOWN_MAX_S)
        self.state = EJECTED
        self.ejected_at = now
        self.probe_inflight = 0
        self.ejections += 1

    def routable(self, now: float) -> bool:
        """Healthy/Suspect: always. Ejected: only as a half-open probe —
        cooldown elapsed AND the probe circuit has a free slot."""
        if self.state != EJECTED:
            return True
        return (
            now - self.ejected_at >= self.cooldown_s
            and self.probe_inflight < PROBE_MAX_INFLIGHT
        )

    def depth_penalty(self) -> float:
        """Extra effective depth in pick(): Suspects are deprioritized
        (routed only when the healthy fleet is busier than the
        penalty), Healthy replicas carry none."""
        return SUSPECT_DEPTH_PENALTY if self.state == SUSPECT else 0.0


def is_gray(h: ReplicaHealth, fleet_median_s: float) -> bool:
    """The gray-failure verdict: enough samples, above the absolute
    floor, and ``GRAY_FACTOR`` beyond the fleet's median latency EWMA
    (median of the OTHER replicas — the caller computes it, so one slow
    replica can't drag the reference toward itself)."""
    return (
        h.samples >= GRAY_MIN_SAMPLES
        and h.latency_ewma is not None
        and h.latency_ewma >= GRAY_FLOOR_S
        and fleet_median_s > 0.0
        and h.latency_ewma >= GRAY_FACTOR * fleet_median_s
    )


__all__ = [
    "EJECTED",
    "EJECT_AFTER_ERRORS",
    "EJECT_COOLDOWN_MAX_S",
    "EJECT_COOLDOWN_S",
    "EJECT_DEADLINE_RATIO",
    "DEADLINE_MIN_SAMPLES",
    "DEADLINE_WINDOW",
    "GRAY_FACTOR",
    "GRAY_FLOOR_S",
    "GRAY_MIN_SAMPLES",
    "HEALTHY",
    "PROBE_MAX_INFLIGHT",
    "ReplicaHealth",
    "SUSPECT",
    "SUSPECT_AFTER_ERRORS",
    "SUSPECT_DEPTH_PENALTY",
    "is_gray",
]
