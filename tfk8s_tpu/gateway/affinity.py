"""Prefix-affinity routing: a consistent-hash ring over the replica set.

Multi-turn sessions win when every turn lands on the replica that
already holds their history's KV pages (the per-replica prefix cache,
runtime/paging.py). The RouteTable's depth-only pick scatters turns
across the fleet and re-prefills the whole history each time; this
module adds the cache-aware layer UNDER the existing health machinery:

- the **affinity key** is the page-aligned prefix digest chain the
  replica's cache will compute for the same prompt — specifically the
  FIRST full page's digest, which is stable as a session's history
  grows (history is append-only, so page 0 never changes) and shared by
  sessions with a common system prefix, co-locating exactly the
  requests whose pages dedup. Follow-up turns carry an explicit
  ``x-tfk8s-session`` token instead (the gateway echoes the key it
  routed by; :class:`~tfk8s_tpu.gateway.client.GatewayClient` sends it
  back), so a session stays pinned even where prompt hashing would
  drift.
- the **ring** (:class:`AffinityRing`) maps keys to replicas with
  ``vnodes`` points per member, so membership churn reassigns only the
  leaving member's keys (the consistent-hash property, test-pinned).
  The ring tracks MEMBERSHIP only; health and load stay the
  RouteTable's: a pick walks the ring successors and takes the first
  ROUTABLE candidate (an Ejected replica falls off the walk and its
  keys land on its successor), and spills to plain least-depth when the
  affine choice is more than ``AFFINITY_SPILL_DEPTH`` effective
  requests deeper than the least-loaded replica — cache hits are worth
  a bounded wait, never a hot spot.
- the **cache directory** (runtime/kvtier/directory.py, ISSUE 17) sits
  ABOVE the ring: when a serve carries a ``KVTierPolicy``, the gateway
  aggregates per-replica digest reports and a fresh directory hit
  overrides the consistent-hash guess — the ring predicts where a
  prefix SHOULD be, the directory knows where it IS (scale-ups remap
  the ring, evictions drop entries, disagg imports warm replicas the
  ring never chose). The override obeys its own depth bound,
  ``DIRECTORY_SPILL_DEPTH``: slightly looser than the affine bound,
  because a KNOWN warm cache saves a whole prefill while the ring's
  guess only probably does — but still bounded, for the same
  no-hot-spot reason.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Any, Dict, List, Optional, Sequence

from tfk8s_tpu.runtime.paging import prefix_digest_chain

#: hash points per ring member — enough that one member's share of the
#: key space stays near 1/n with low variance at fleet sizes this
#: operator runs (single digits)
VNODES = 64
#: effective-depth gap (vs the least-loaded routable replica) past which
#: an affine pick spills to least-depth: a cache hit saves one prefill,
#: not unbounded queueing behind a hot key
AFFINITY_SPILL_DEPTH = 4.0
#: the same bound for a cache-DIRECTORY override (runtime/kvtier): a
#: confirmed-warm replica is worth a little more queueing than the
#: ring's statistical guess, but a hot prefix still must not melt one
#: replica while the rest idle
DIRECTORY_SPILL_DEPTH = 6.0


def _point(s: str) -> int:
    """A stable 64-bit ring position for a member vnode or a key."""
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "big")


def affinity_key_of(tokens: Sequence[int], page_size: int) -> str:
    """The routing key for a prompt: the first FULL page's digest from
    the same chain the replica's prefix cache computes (stable across a
    session's turns; shared across sessions with a common first page).
    Prompts shorter than one full page hash whole — no cached pages to
    be affine to, but the key still pins retries of the same prompt."""
    chain = prefix_digest_chain(tokens, page_size, max(len(tokens) - 1, 0) // page_size)
    if chain:
        return chain[0]
    return hashlib.sha256(
        repr([int(t) for t in tokens]).encode()
    ).hexdigest()


class AffinityRing:
    """Consistent-hash ring over replica keys. Not thread-safe — the
    RouteTable mutates and reads it under its own lock, like every other
    routing structure."""

    def __init__(self, vnodes: int = VNODES):
        self._vnodes = max(1, int(vnodes))
        self._members: Dict[str, List[int]] = {}
        self._points: List[int] = []          # sorted vnode positions
        self._owner: Dict[int, str] = {}      # position -> member

    def __contains__(self, member: str) -> bool:
        return member in self._members

    def __len__(self) -> int:
        return len(self._members)

    def members(self) -> List[str]:
        return sorted(self._members)

    def add(self, member: str) -> None:
        if member in self._members:
            return
        pts = []
        for i in range(self._vnodes):
            p = _point(f"{member}#{i}")
            if p in self._owner:  # vanishing-probability collision
                continue
            self._owner[p] = member
            bisect.insort(self._points, p)
            pts.append(p)
        self._members[member] = pts

    def remove(self, member: str) -> None:
        for p in self._members.pop(member, []):
            del self._owner[p]
            i = bisect.bisect_left(self._points, p)
            del self._points[i]

    def candidates(self, key: str, limit: Optional[int] = None) -> List[str]:
        """Members in successor order from the key's ring position —
        the first is the owner; each later one is where the keys land
        when everything before it is unroutable. Distinct members only."""
        if not self._points:
            return []
        limit = len(self._members) if limit is None else limit
        start = bisect.bisect_right(self._points, _point(key))
        seen: List[str] = []
        for off in range(len(self._points)):
            owner = self._owner[self._points[(start + off) % len(self._points)]]
            if owner not in seen:
                seen.append(owner)
                if len(seen) >= limit:
                    break
        return seen

    def owner(self, key: str) -> Optional[str]:
        c = self.candidates(key, limit=1)
        return c[0] if c else None

    def describe(self) -> Dict[str, Any]:
        """Ownership view for ``/debug/routes``: per member, the arc
        count and the fraction of the 64-bit key space it owns."""
        span = 1 << 64
        owned: Dict[str, Dict[str, Any]] = {
            m: {"vnodes": len(pts), "owned_fraction": 0.0}
            for m, pts in self._members.items()
        }
        n = len(self._points)
        for i, p in enumerate(self._points):
            nxt = self._points[(i + 1) % n]
            arc = (nxt - p) % span or span
            # keys in (p, nxt] belong to nxt's owner
            owned[self._owner[nxt]]["owned_fraction"] += arc / span
        for info in owned.values():
            info["owned_fraction"] = round(info["owned_fraction"], 4)
        return {
            "vnodes_per_member": self._vnodes,
            "members": {m: owned[m] for m in sorted(owned)},
        }


__all__ = [
    "AFFINITY_SPILL_DEPTH",
    "DIRECTORY_SPILL_DEPTH",
    "AffinityRing",
    "VNODES",
    "affinity_key_of",
]
