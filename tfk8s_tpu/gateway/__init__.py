"""TPUGateway: the HTTP serving front door (ISSUE 10 / ROADMAP item 3).

One wire entrance for inference traffic: an HTTP transport on the
apiserver's proven stack (:mod:`tfk8s_tpu.gateway.server`), least-queue-
depth routing over the replicas' published load signal
(:mod:`tfk8s_tpu.gateway.router`), and per-tenant token-bucket admission
with priority shedding (:mod:`tfk8s_tpu.gateway.admission`). The thin
pipelined client lives in :mod:`tfk8s_tpu.gateway.client`.
"""

from tfk8s_tpu.gateway.admission import TenantAdmission, shed_threshold
from tfk8s_tpu.gateway.client import GatewayClient
from tfk8s_tpu.gateway.router import RouteTable
from tfk8s_tpu.gateway.server import GatewayServer

__all__ = [
    "GatewayClient",
    "GatewayServer",
    "RouteTable",
    "TenantAdmission",
    "shed_threshold",
]
