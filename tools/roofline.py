"""Roofline probe harness — the committed, re-runnable evidence behind
PERF_RESNET.md (VERDICT r3 next #2: "a perf claim this central must be
one command away").

Measures, on the CURRENT backend:

- ``matmul_tflops``      — bf16 [n,n] matmul chain (MXU ceiling)
- ``stream_bf16_gbps``   — elementwise read+write streaming, bf16
- ``stream_f32_gbps``    — same in f32 (HBM-bandwidth ceiling as XLA
                           fusions see it)
- ``pallas_copy_gbps``   — a Pallas block-copy kernel (what hand-written
                           kernel DMA achieves on this rig)
- ``resnet_fwd_ms``      — ResNet-50 batch-256 forward only
- ``resnet_gn_ablated_ms`` — full train step with every GroupNorm
                           replaced by identity (models/resnet.ablate_norm)
- ``resnet_step_ms``     — full train step (same probe bench.py times)

Every timed region ends in a HOST FETCH of a device scalar — through the
remote-execution tunnel ``block_until_ready`` returns early
(BENCH_BASELINE.json note), so a transfer is the only honest barrier.
Loop bodies thread their data through the scan carry so XLA cannot hoist
the work out of the timed region (the round-3 measurement trap).

Run standalone (``python tools/roofline.py``, one JSON line) or via
``python bench.py --roofline``; the default bench run embeds this block
in its output so every BENCH_r*.json records the platform envelope the
headline claim is judged against. ``BENCH_SMALL=1`` shrinks shapes for a
seconds-scale CPU smoke run.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _median_fetch(timed_once, windows: int = 3):
    """(median_seconds, all_window_seconds) — bench.py's timing helper,
    shared so the two measurement paths cannot drift."""
    import bench

    return bench._median_window(timed_once, windows)


def _diff_seconds_per_iter(make_run, n1: int, n2: int) -> float:
    """Per-iteration seconds via DIFFERENCING two scan lengths: the
    remote-execution tunnel adds a large fixed cost per dispatched
    window (~100 ms round trip measured — it swamped 8-iteration probes
    at 2-3x error), and (t(n2) - t(n1)) / (n2 - n1) cancels any fixed
    per-window overhead exactly. ``make_run(iters)`` returns a warmed
    no-arg callable that runs AND host-syncs one window."""
    run1, run2 = make_run(n1), make_run(n2)
    t1, _ = _median_fetch(run1)
    t2, _ = _median_fetch(run2)
    if t2 <= t1:
        # noise exceeded signal (short windows on a contended host) — an
        # absurd rate in the artifact would be worse than a missing one
        raise RuntimeError(
            f"non-monotonic probe windows: t({n1})={t1:.4f}s >= "
            f"t({n2})={t2:.4f}s; raise the iteration counts"
        )
    return (t2 - t1) / (n2 - n1)


def matmul_tflops(n: int = 8192, n1: int = 8, n2: int = 40) -> float:
    """bf16 matmul chain: the MXU ceiling this rig can reach."""
    import jax
    import jax.numpy as jnp

    w = (jnp.eye(n, dtype=jnp.bfloat16)
         + jnp.ones((n, n), jnp.bfloat16) * jnp.bfloat16(1e-3))
    x = jnp.ones((n, n), jnp.bfloat16)

    def make_run(iters):
        def run(x, w):
            def body(c, _):
                # rescale so magnitudes stay O(1) across the chain
                return (c @ w * jnp.bfloat16(0.5)).astype(jnp.bfloat16), ()

            y = jax.lax.scan(body, x, None, length=iters)[0]
            return jnp.sum(y.astype(jnp.float32))

        run = jax.jit(run)
        float(run(x, w))  # compile + warm
        return lambda: float(run(x, w))

    sec = _diff_seconds_per_iter(make_run, n1, n2)
    return 2 * n**3 / sec / 1e12


def stream_gbps(dtype_name: str, elems: int = 2**28,
                n1: int = 8, n2: int = 72) -> float:
    """Elementwise streaming: each iteration reads and writes the full
    buffer once → bytes/iter = 2 * size."""
    import jax
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if dtype_name == "bf16" else jnp.float32
    x = jnp.ones((elems,), dtype)

    def make_run(iters):
        def run(x):
            def body(c, _):
                return c + dtype(1), ()

            y = jax.lax.scan(body, x, None, length=iters)[0]
            # full reduction (not a slice): a sliceable output would let
            # XLA shrink the streamed region and fake the number
            return jnp.sum(y.astype(jnp.float32))

        run = jax.jit(run)
        float(run(x))
        return lambda: float(run(x))

    sec = _diff_seconds_per_iter(make_run, n1, n2)
    nbytes = x.dtype.itemsize * elems
    return 2 * nbytes / sec / 1e9


def pallas_copy_gbps(rows: int = 8192, cols: int = 8192,
                     n1: int = 4, n2: int = 36,
                     block_rows: int = 64) -> float:
    """HBM→VMEM→HBM block copy as a Pallas kernel — the DMA bandwidth
    hand-written kernels see (~0.5x of the XLA streaming number on this
    rig; PERF_RESNET.md §1). Block is 64 rows (2 MB f32): in+out with
    double buffering must fit the 16 MB scoped-VMEM limit. Raises on
    backends without Pallas (run_all marks it degraded)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    interpret = jax.devices()[0].platform not in ("tpu", "axon")

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    copy = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, cols), jnp.float32),
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, cols), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, cols), lambda i: (i, 0)),
        interpret=interpret,
    )

    x = jnp.ones((rows, cols), jnp.float32)

    def make_run(iters):
        def run(x):
            def body(c, _):
                return copy(c), ()

            y = jax.lax.scan(body, x, None, length=iters)[0]
            return jnp.sum(y[:8, :8])  # copies can't be shrunk by slicing

        run = jax.jit(run)
        float(run(x))
        return lambda: float(run(x))

    # raises on pallas-unsupported backends AND on non-monotonic windows —
    # run_all's probe() turns either into a degraded_probes marker, so
    # "unsupported" and "too noisy this run" are both visible (the
    # DMA-ceiling argument in PERF_RESNET.md leans on this field)
    sec = _diff_seconds_per_iter(make_run, n1, n2)
    return 2 * rows * cols * 4 / sec / 1e9


def _resnet_task_kw(small: bool) -> Dict:
    if small:
        return dict(depth=18, num_classes=8, image_size=32, width=8, batch_size=8)
    return dict(depth=50, num_classes=1000, image_size=224, batch_size=256)


def resnet_fwd_ms(small: bool, iters: int = 40) -> float:
    """Forward-only ResNet step (loss, no grad/optimizer): isolates the
    backward+update cost in the step-time decomposition."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfk8s_tpu.models import resnet
    from tfk8s_tpu.parallel.mesh import make_mesh
    from tfk8s_tpu.runtime.train import TrainConfig, Trainer

    task = resnet.make_task(**_resnet_task_kw(small))
    mesh = make_mesh(data=jax.device_count())
    trainer = Trainer(task, TrainConfig(steps=1), mesh)
    state = trainer.init_state()
    batch = jax.device_put(
        task.make_batch(np.random.default_rng(0), task.batch_size),
        trainer.batch_shardings,
    )

    def fwd(params, batch):
        def body(carry, _):
            # thread the carry into the INPUT so XLA cannot hoist the
            # loop-invariant forward out of the scan (r3 timing trap)
            b = jax.tree_util.tree_map(
                lambda x: x + carry.astype(x.dtype) * 0
                if jnp.issubdtype(x.dtype, jnp.floating)
                else x,
                batch,
            )
            loss, _aux = task.loss_fn(params, b, jax.random.key(0))
            return loss.astype(jnp.float32), ()

        return jax.lax.scan(body, jnp.float32(0), None, length=iters)[0]

    run = jax.jit(fwd)
    float(run(state.params, batch))

    sec, _ = _median_fetch(lambda: float(run(state.params, batch)))
    return sec / iters * 1000


def resnet_step_ms(small: bool, ablate_norm: bool = False,
                   steps: Optional[int] = None) -> float:
    """Full train step via bench.py's scanned timer; ``ablate_norm``
    swaps every GroupNorm for identity (the memory-bound ablation:
    PERF_RESNET.md §4's GN-ablated row)."""
    import contextlib

    import jax

    import bench
    from tfk8s_tpu.models import resnet
    from tfk8s_tpu.parallel.mesh import make_mesh

    steps = steps or (4 if small else 20)
    scope = resnet.ablate_norm() if ablate_norm else contextlib.nullcontext()
    with scope:
        task = resnet.make_task(**_resnet_task_kw(small))
        mesh = make_mesh(data=jax.device_count())
        sec_per_step, _windows = bench._time_task(task, mesh, steps)
        return sec_per_step * 1000


def run_all(small: Optional[bool] = None,
            include_resnet: bool = True) -> Dict:
    """Every probe, one dict — the block bench.py embeds and
    PERF_RESNET.md's tables regenerate from."""
    import jax

    if small is None:
        small = os.environ.get("BENCH_SMALL") == "1"
    if small:
        mm_kw = dict(n=512, n1=2, n2=10)
        st_kw = dict(elems=2**20, n1=2, n2=10)
        pc_kw = dict(rows=256, cols=256, n1=2, n2=6, block_rows=64)
        fwd_iters = 10
    else:
        mm_kw = dict(n=8192)
        st_kw = dict(elems=2**28)
        pc_kw = {}
        fwd_iters = 40

    out: Dict = {
        "platform": jax.devices()[0].platform,
        "n_chips": jax.device_count(),
        "small": small,
    }
    degraded = []

    def probe(name, fn):
        # per-probe degradation: a noisy/failed probe costs its field and
        # gets a marker, never an absurd number or a dead harness
        try:
            out[name] = round(fn(), 1)
        except Exception as exc:  # noqa: BLE001
            print(f"roofline: {name} probe failed: {exc}", file=sys.stderr)
            degraded.append(name)

    probe("matmul_tflops", lambda: matmul_tflops(**mm_kw))
    probe("stream_bf16_gbps", lambda: stream_gbps("bf16", **st_kw))
    probe("stream_f32_gbps", lambda: stream_gbps("f32", **st_kw))
    probe("pallas_copy_gbps", lambda: pallas_copy_gbps(**pc_kw))
    if include_resnet:
        probe("resnet_fwd_ms", lambda: resnet_fwd_ms(small, iters=fwd_iters))
        probe(
            "resnet_gn_ablated_step_ms",
            lambda: resnet_step_ms(small, ablate_norm=True),
        )
    if degraded:
        out["degraded_probes"] = degraded
    return out


def main() -> None:
    if os.environ.get("BENCH_PLATFORM"):
        from tfk8s_tpu.runtime.launcher import force_platform

        force_platform(os.environ["BENCH_PLATFORM"])
    # standalone runs include the full-step row too, so the memory-bound
    # argument (step vs fwd vs GN-ablated vs stream) closes in one output;
    # a late failure costs its row, never the already-measured output
    out = run_all()
    try:
        out["resnet_step_ms"] = round(resnet_step_ms(out["small"]), 1)
    except Exception as exc:  # noqa: BLE001
        print(f"roofline: resnet_step_ms probe failed: {exc}", file=sys.stderr)
        out["degraded_probes"] = out.get("degraded_probes", []) + [
            "resnet_step_ms"
        ]
    print(json.dumps({"metric": "roofline", **out}))


if __name__ == "__main__":
    main()
