"""The one lint driver: discover → parse once → run every checker →
apply suppressions.

``run_lint`` is the in-process API tier-1 uses (no subprocess per
checker); ``python -m tools.lint`` (``tools/lint/__main__.py``) is the
same call with argv plumbing.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from tools.lint.base import Checker, Finding, Module, Suppression

_HERE = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(os.path.dirname(_HERE))
DEFAULT_SUPPRESSIONS = os.path.join(_HERE, "suppressions.txt")


def default_paths() -> List[str]:
    """The scope tier-1 enforces: the package, the tools, the repo-root
    bench script (metric registrations), and the seeded chaos harness
    (tests/chaos.py — the one tests/ file carrying a seeded-path
    invariant). Checkers narrow further via ``Checker.relevant``."""
    return [
        os.path.join(REPO_ROOT, "tfk8s_tpu"),
        os.path.join(REPO_ROOT, "tools"),
        os.path.join(REPO_ROOT, "bench.py"),
        os.path.join(REPO_ROOT, "tests", "chaos.py"),
    ]


def _discover(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """(abspath, relpath) for every .py under ``paths``, sorted by
    relpath so output and graph construction are deterministic."""
    out: Dict[str, str] = {}
    for root in paths:
        root = os.path.abspath(root)
        if os.path.isfile(root):
            out[os.path.relpath(root, REPO_ROOT).replace(os.sep, "/")] = root
            continue
        for dirpath, dirnames, names in os.walk(root):
            dirnames[:] = [d for d in dirnames if not d.startswith((".", "__pycache__"))]
            for n in names:
                if n.endswith(".py"):
                    p = os.path.join(dirpath, n)
                    out[os.path.relpath(p, REPO_ROOT).replace(os.sep, "/")] = p
    return sorted((rel, p) for rel, p in out.items())


def load_modules(paths: Sequence[str]) -> Tuple[List[Module], List[str]]:
    """Parse every discovered file once. Unparseable files are reported
    as errors (a syntax error must fail the lint, not hide code from
    it)."""
    modules: List[Module] = []
    errors: List[str] = []
    for rel, p in _discover(paths):
        with open(p, encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as e:
            errors.append(f"{rel}:{e.lineno}: syntax error: {e.msg}")
            continue
        modules.append(Module(path=p, relpath=rel, tree=tree, source=src))
    return modules, errors


def load_suppressions(path: str = DEFAULT_SUPPRESSIONS) -> Tuple[List[Suppression], List[str]]:
    """Parse the suppressions file. Format, one per line::

        <checker>:<relpath>:<qualname>:<detail>  # why this is acceptable

    Globs are allowed in every field. The reason is MANDATORY — a key
    with no ``#`` comment is itself reported as a lint problem."""
    sups: List[Suppression] = []
    errors: List[str] = []
    if not os.path.exists(path):
        return sups, errors
    with open(path, encoding="utf-8") as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            pattern, _, reason = line.partition("#")
            pattern, reason = pattern.strip(), reason.strip()
            if not reason:
                errors.append(
                    f"suppressions.txt:{lineno}: suppression without a "
                    f"reason (add '# why'): {pattern!r}"
                )
                continue
            if pattern.count(":") < 3:
                errors.append(
                    f"suppressions.txt:{lineno}: malformed key (need "
                    f"checker:relpath:qualname:detail): {pattern!r}"
                )
                continue
            sups.append(Suppression(pattern=pattern, reason=reason, lineno=lineno))
    return sups, errors


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)  # unsuppressed
    suppressed: List[Tuple[Finding, Suppression]] = field(default_factory=list)
    unused_suppressions: List[Suppression] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)  # parse/format problems

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors

    @property
    def clean(self) -> bool:
        """ok AND no dead weight in the suppressions file — the bar the
        tier-1 test holds the tree to."""
        return self.ok and not self.unused_suppressions


def run_lint(
    paths: Optional[Sequence[str]] = None,
    checkers: Optional[Sequence[Checker]] = None,
    suppressions_path: str = DEFAULT_SUPPRESSIONS,
    suppress: bool = True,
) -> LintResult:
    from tools.lint.checkers import all_checkers

    result = LintResult()
    modules, errors = load_modules(paths or default_paths())
    result.errors.extend(errors)
    sups: List[Suppression] = []
    if suppress:
        sups, sup_errors = load_suppressions(suppressions_path)
        result.errors.extend(sup_errors)
    for checker in checkers if checkers is not None else all_checkers():
        scoped = [m for m in modules if checker.relevant(m.relpath)]
        for finding in checker.check(scoped):
            hit = next((s for s in sups if s.matches(finding.key)), None)
            if hit is not None:
                hit.used = True
                result.suppressed.append((finding, hit))
            else:
                result.findings.append(finding)
    result.unused_suppressions = [s for s in sups if not s.used]
    result.findings.sort(key=lambda f: (f.relpath, f.line, f.checker))
    return result
