"""seeded-determinism: the seeded paths — data augmentation, the chaos
harness, checkpoint discovery — must be pure functions of their seeds.
A ``time.time()`` or module-state RNG call in one of them silently
breaks replayability (same seed, different batch) and the elastic
resume contract.

Flagged inside the scoped files: ``time.time`` / ``time.time_ns`` /
``datetime.now`` / ``utcnow``, ``uuid.uuid4``, ``os.urandom``,
``secrets.*``, module-state ``random.*`` (``random.random``,
``random.shuffle``...), and module-state ``np.random.*``
(``np.random.rand``...).

Explicitly allowed: constructing SEEDED generator objects —
``random.Random(seed)``, ``np.random.default_rng(seed)``,
``np.random.SeedSequence(entropy)`` / ``Generator`` / ``PCG64`` /
``Philox`` / ``MT19937`` — and anything called on such an object,
including inline chains like ``np.random.default_rng(seq).shuffle(x)``.
The SAME constructors called with NO arguments are flagged: an argless
``default_rng()`` / ``Random()`` / ``SeedSequence()`` pulls OS entropy,
which is exactly the nondeterminism this checker exists to keep out.
``time.monotonic`` / ``perf_counter`` are allowed: they are for
durations and never persisted into data.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tools.lint.base import Checker, Finding, Module, QualnameVisitor, dotted_name

# the seeded paths; everything else may use wall clocks freely
SCOPE_PREFIXES = (
    "tfk8s_tpu/data/",
    "tfk8s_tpu/runtime/checkpoint.py",
    # per-request sampling PRNG (seed + absolute-position fold) must
    # survive resume bit-identically — no wall-clock or ambient RNG
    "tfk8s_tpu/runtime/sched/",
    # KV tiering (ISSUE 17): restores and directory staleness must be
    # reproducible — monotonic clocks only, injected for tests
    "tfk8s_tpu/runtime/kvtier/",
    "tests/chaos.py",
)

_BANNED_EXACT = {
    "time.time", "time.time_ns", "uuid.uuid4", "os.urandom",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
}
_BANNED_PREFIXES = ("secrets.",)
_RNG_MODULES = ("random.", "np.random.", "numpy.random.")
_ALLOWED_RNG_CONSTRUCTORS = {
    "random.Random",
    "np.random.default_rng", "numpy.random.default_rng",
    "np.random.SeedSequence", "numpy.random.SeedSequence",
    "np.random.Generator", "numpy.random.Generator",
    "np.random.PCG64", "numpy.random.PCG64",
    "np.random.Philox", "numpy.random.Philox",
    "np.random.MT19937", "numpy.random.MT19937",
}


class _CallVisitor(QualnameVisitor):
    def __init__(self, checker: "SeededDeterminismChecker", module: Module):
        super().__init__()
        self.checker = checker
        self.module = module
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        callee = dotted_name(node.func)
        if callee is not None and self._banned(node, callee):
            self.findings.append(Finding(
                checker=self.checker.name,
                relpath=self.module.relpath,
                line=node.lineno,
                qualname=self.qualname,
                detail=f"call:{callee}",
                message=(
                    f"{callee}() in a seeded path — wall clock / module-state "
                    f"RNG breaks same-seed replay; use the injected generator "
                    f"or an explicit seed"
                ),
            ))
        self.generic_visit(node)

    def _banned(self, node: ast.Call, callee: str) -> bool:
        if callee in _BANNED_EXACT:
            return True
        if callee.startswith(_BANNED_PREFIXES):
            return True
        if callee in _ALLOWED_RNG_CONSTRUCTORS:
            # the constructor itself: seeded ok, argless = OS entropy
            return not (node.args or node.keywords)
        # a method chained off a constructed generator:
        # np.random.default_rng(seq).shuffle(x) — allowed iff the inner
        # constructor call is seeded (the inner Call is visited
        # separately and catches the argless case, so don't double-flag)
        for ctor in _ALLOWED_RNG_CONSTRUCTORS:
            if callee.startswith(ctor + "()."):
                return False
        return callee.startswith(_RNG_MODULES)


class SeededDeterminismChecker(Checker):
    name = "seeded-determinism"

    def __init__(self, scope_prefixes=SCOPE_PREFIXES):
        self.scope_prefixes = tuple(scope_prefixes)

    def relevant(self, relpath: str) -> bool:
        return relpath.startswith(self.scope_prefixes)

    def check(self, modules: List[Module]) -> Iterable[Finding]:
        for module in modules:
            visitor = _CallVisitor(self, module)
            visitor.visit(module.tree)
            yield from visitor.findings
