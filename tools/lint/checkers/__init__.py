"""The six checkers. ``all_checkers()`` is the driver's registry —
order here is the order findings are attributed, so keep it stable."""

from __future__ import annotations

from typing import List

from tools.lint.base import Checker
from tools.lint.checkers.blocking_under_lock import BlockingUnderLockChecker
from tools.lint.checkers.frozen_mutation import FrozenMutationChecker
from tools.lint.checkers.lock_order import LockOrderChecker
from tools.lint.checkers.metric_names import MetricNamesChecker
from tools.lint.checkers.seeded_determinism import SeededDeterminismChecker
from tools.lint.checkers.typed_errors import TypedErrorsChecker


def all_checkers() -> List[Checker]:
    return [
        LockOrderChecker(),
        BlockingUnderLockChecker(),
        FrozenMutationChecker(),
        TypedErrorsChecker(),
        SeededDeterminismChecker(),
        MetricNamesChecker(),
    ]
