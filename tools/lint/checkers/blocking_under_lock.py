"""blocking-under-lock: nothing that can stall other threads runs
inside a held lock region.

Flagged while at least one lock is held:

- ``time.sleep`` (and ``time.sleep``-shaped aliases);
- file / fd IO: ``open(...)``, ``os.fsync`` / ``os.fdatasync`` /
  ``os.replace`` / ``os.rename``, ``shutil.*``, ``subprocess.*``;
- socket-ish calls: ``socket.create_connection``, receiver methods
  ``connect`` / ``accept`` / ``recv`` / ``recv_into`` / ``sendall``,
  ``urllib.request.urlopen``, ``http.client`` requests;
- ``<x>.join()`` with no arguments (a thread/process join with no
  timeout; ``sep.join(parts)`` takes an argument and is never flagged);
- ``<cond>.wait()`` / ``wait_for`` WITHOUT a timeout when the waiter
  holds any OTHER lock than the condition's own underlying lock (the
  standard ``with cond: cond.wait()`` pattern is exempt, including
  through ``threading.Condition(self._lock)`` aliases);
- jit dispatch: any ``jax.*`` / ``jnp.*`` call or ``block_until_ready``.

One level of propagation: calling a same-class method / same-module
function that DIRECTLY contains one of the primitives above is flagged
at the call site (``self._compact()`` under the commit lock). Deeper
transitive chains are out of scope by design — depth 1 already covers
the repo's real layering and deeper propagation turns every helper into
a false positive cascade.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.lint.base import Checker, Finding, Module, dotted_name
from tools.lint.locks import ModuleLocks

_SLEEPS = {"time.sleep"}
_FILE_IO = {
    "os.fsync", "os.fdatasync", "os.sync", "os.replace", "os.rename",
    "os.remove", "os.unlink", "os.makedirs",
    "shutil.copy", "shutil.copy2", "shutil.copytree", "shutil.move",
    "shutil.rmtree",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
}
_NET = {"socket.create_connection", "urllib.request.urlopen"}
_SOCKET_METHODS = {"connect", "accept", "recv", "recv_into", "sendall",
                   "makefile", "getresponse"}
_WAITS = {"wait", "wait_for"}
_JIT_PREFIXES = ("jax.", "jnp.")


def _primitive(call: ast.Call, callee: Optional[str]) -> Optional[str]:
    """Classify a call as a blocking primitive (lock-context-free part).
    Returns a short tag or None."""
    if callee is None:
        return None
    if callee in _SLEEPS:
        return "sleep"
    if callee == "open" or callee in _FILE_IO:
        return "file-io"
    if callee in _NET:
        return "net-io"
    if callee.startswith(_JIT_PREFIXES) or callee.endswith(".block_until_ready"):
        return "jit-dispatch"
    attr = callee.rsplit(".", 1)[-1]
    if "." in callee and attr in _SOCKET_METHODS:
        return "net-io"
    if attr == "join" and not call.args and not call.keywords:
        return "join"
    return None


def _wait_without_timeout(call: ast.Call, callee: Optional[str]) -> bool:
    if callee is None or "." not in callee:
        return False
    if callee.rsplit(".", 1)[-1] not in _WAITS:
        return False
    has_timeout = bool(call.args) or any(
        kw.arg in ("timeout", None) for kw in call.keywords
    )
    # wait_for(pred) with no timeout arg is still unbounded
    if callee.endswith("wait_for") and len(call.args) == 1 and not call.keywords:
        has_timeout = False
    return not has_timeout


class BlockingUnderLockChecker(Checker):
    name = "blocking-under-lock"

    def check(self, modules: List[Module]) -> Iterable[Finding]:
        mods = [ModuleLocks(m) for m in modules]

        # pass 1: which functions DIRECTLY contain a blocking primitive
        # (for depth-1 call-site propagation). Condition-waits count
        # here even when locally exempt: they still block the caller.
        blocking_fns: Dict[Tuple[str, str], str] = {}
        for ml in mods:
            for fn in ml.functions:
                for call in fn.calls:
                    tag = _primitive(call.node, call.callee)
                    if tag is None and _wait_without_timeout(call.node, call.callee):
                        tag = "cond-wait"
                    if tag is not None:
                        blocking_fns.setdefault(
                            (ml.module.dotted, fn.qualname), tag
                        )
                        break

        # pass 2: calls made while holding a lock
        for ml in mods:
            rel = ml.module.relpath
            for fn in ml.functions:
                for call in fn.calls:
                    if not call.held:
                        continue
                    tag = _primitive(call.node, call.callee)
                    if tag is not None:
                        yield self._finding(rel, fn.qualname, call.line,
                                            f"{tag}:{call.callee}", call.held)
                        continue
                    if _wait_without_timeout(call.node, call.callee):
                        # exempt: waiting on (an alias of) a lock we hold,
                        # and it is the ONLY lock held
                        recv = call.node.func.value  # type: ignore[union-attr]
                        recv_id = ml.lock_id(recv, fn.cls)
                        others = [h for h in call.held if h != recv_id]
                        if others:
                            yield self._finding(
                                rel, fn.qualname, call.line,
                                f"cond-wait:{call.callee}", tuple(others))
                        continue
                    # depth-1 propagation through local calls
                    target = self._local_target(ml, fn, call.callee)
                    if target is not None and target in blocking_fns:
                        yield self._finding(
                            rel, fn.qualname, call.line,
                            f"call:{call.callee}", call.held,
                            because=blocking_fns[target])

    @staticmethod
    def _local_target(ml: ModuleLocks, fn, callee: Optional[str]):
        if callee is None:
            return None
        if callee.startswith("self.") and fn.cls:
            meth = callee[len("self."):]
            if "." not in meth:
                return (ml.module.dotted, f"{fn.cls}.{meth}")
            return None
        if "." not in callee:
            return (ml.module.dotted, callee)
        return None

    def _finding(self, rel: str, qual: str, line: int, detail: str,
                 held: Tuple[str, ...], because: Optional[str] = None) -> Finding:
        what = detail.split(":", 1)[0]
        msg = {
            "sleep": "sleep while holding",
            "file-io": "file IO while holding",
            "net-io": "socket/network IO while holding",
            "jit-dispatch": "jit dispatch while holding",
            "join": "unbounded join() while holding",
            "cond-wait": "condition wait without timeout while holding",
            "call": "call into blocking code while holding",
        }[what]
        suffix = f" (callee directly does {because})" if because else ""
        return Finding(
            checker=self.name, relpath=rel, line=line, qualname=qual,
            detail=detail,
            message=f"{detail.split(':', 1)[1]}: {msg} {', '.join(held)}{suffix}",
        )
