"""frozen-mutation: objects read from the store / informer / lister
paths are shared frozen instances — writing to one without an
intervening ``thaw()`` / ``deepcopy`` is a bug that (best case) raises
``FrozenObjectError`` at runtime and (worst case, plain dicts) corrupts
every other reader.

Taint sources (intra-function, linear order):

- ``x = <recv>.get(...)`` / ``.get_by_key(...)`` / ``.list(...)`` where
  the receiver's dotted name contains a store/lister/indexer/informer
  word (``self.store``, ``self._indexer``, ``job_lister``...);
- ``items, rv = store.list(...)`` tuple unpacking taints each target;
- ``x = ev.object`` (watch event payloads are frozen too);
- iterating or subscripting a tainted collection taints the loop/element
  variable.

Cleared by rebinding: ``x = thaw(x)``, ``x = copy.deepcopy(x)``,
``x = dataclasses.replace(...)``, or any other assignment to the name.
Flags: attribute/subscript writes rooted at a tainted name, augmented
assigns, and in-place mutator method calls (``append``/``update``/
``pop``/``sort``/...). Store WRITE verbs (create/update/patch) return
private copies, so their results are deliberately not tainted.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from tools.lint.base import Checker, Finding, Module, dotted_name

_READ_VERBS = {"get", "get_by_key", "list"}
_SOURCE_WORDS = ("store", "lister", "indexer", "informer", "cache")
_CLEARERS = ("thaw", "deepcopy", "replace", "roundtrip", "to_dict", "from_dict")
_MUTATORS = {
    "append", "extend", "insert", "pop", "popitem", "remove", "clear",
    "sort", "reverse", "update", "setdefault", "add", "discard",
}
_EVENT_NAMES = {"ev", "event", "evt"}


def _is_source_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    callee = dotted_name(node.func)
    if callee is None or "." not in callee:
        return False
    base, _, verb = callee.rpartition(".")
    if verb not in _READ_VERBS:
        return False
    return any(w in base.lower() for w in _SOURCE_WORDS)


def _is_event_object(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "object"
        and isinstance(node.value, ast.Name)
        and node.value.id in _EVENT_NAMES
    )


def _root_name(node: ast.AST) -> Optional[str]:
    """The base Name of an attribute/subscript chain (``x.a[0].b`` → x)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _FunctionScan:
    def __init__(self, checker: "FrozenMutationChecker", rel: str, qual: str):
        self.checker = checker
        self.rel = rel
        self.qual = qual
        self.tainted: Set[str] = set()
        self.findings: List[Finding] = []

    # -- taint bookkeeping ---------------------------------------------------

    def _value_taints(self, value: ast.AST) -> bool:
        if _is_source_call(value) or _is_event_object(value):
            return True
        # x = tainted / x = tainted[0] / x = tainted.field
        root = _root_name(value)
        return root is not None and root in self.tainted

    def _assign(self, targets: List[ast.expr], value: ast.AST) -> None:
        taints = self._value_taints(value)
        # a clearing call always un-taints its targets, even when fed a
        # tainted argument — that is the whole point of thaw()/deepcopy
        if isinstance(value, ast.Call):
            callee = dotted_name(value.func) or ""
            if callee.rsplit(".", 1)[-1] in _CLEARERS:
                taints = False
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                (self.tainted.add if taints else self.tainted.discard)(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for el in tgt.elts:
                    if isinstance(el, ast.Name):
                        (self.tainted.add if taints else self.tainted.discard)(el.id)
            elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
                root = _root_name(tgt)
                if root in self.tainted:
                    self._flag(tgt, f"{root}.{_describe(tgt)}=", tgt.lineno)

    def _flag(self, node: ast.AST, detail: str, line: int) -> None:
        self.findings.append(Finding(
            checker=self.checker.name, relpath=self.rel, line=line,
            qualname=self.qual, detail=detail,
            message=(
                f"write '{detail}' to an object from a frozen read path "
                f"without thaw()/deepcopy"
            ),
        ))

    # -- statement walk (source order) ---------------------------------------

    def walk(self, stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Assign):
                self._assign(stmt.targets, stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self._assign([stmt.target], stmt.value)
            elif isinstance(stmt, ast.AugAssign):
                root = _root_name(stmt.target)
                if root in self.tainted and not isinstance(stmt.target, ast.Name):
                    self._flag(stmt.target, f"{root}.{_describe(stmt.target)}+=",
                               stmt.lineno)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                iter_root = _root_name(stmt.iter)
                loop_taints = (
                    (iter_root is not None and iter_root in self.tainted)
                    or self._value_taints(stmt.iter)
                )
                for el in ast.walk(stmt.target):
                    if isinstance(el, ast.Name):
                        (self.tainted.add if loop_taints
                         else self.tainted.discard)(el.id)
            elif isinstance(stmt, ast.Expr):
                self._check_mutator(stmt.value)
            for body in _bodies(stmt):
                self.walk(body)

    def _check_mutator(self, expr: ast.AST) -> None:
        if not (isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute)):
            return
        if expr.func.attr not in _MUTATORS:
            return
        root = _root_name(expr.func.value)
        if root is not None and root in self.tainted:
            self._flag(expr, f"{root}.{expr.func.attr}()", expr.lineno)


def _bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
    out = []
    for name in ("body", "orelse", "finalbody"):
        b = getattr(stmt, name, None)
        if isinstance(b, list) and b and isinstance(b[0], ast.stmt):
            out.append(b)
    for h in getattr(stmt, "handlers", []) or []:
        out.append(h.body)
    return out


def _describe(tgt: ast.AST) -> str:
    if isinstance(tgt, ast.Attribute):
        return tgt.attr
    if isinstance(tgt, ast.Subscript):
        return "[]"
    return "?"


class FrozenMutationChecker(Checker):
    name = "frozen-mutation"

    def check(self, modules: List[Module]) -> Iterable[Finding]:
        for module in modules:
            for node in ast.walk(module.tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                qual = _qualname(module.tree, node)
                scan = _FunctionScan(self, module.relpath, qual)
                scan.walk(node.body)
                yield from scan.findings


def _qualname(tree: ast.Module, target: ast.AST) -> str:
    """Class.method for methods, bare name otherwise (one level — the
    repo does not nest classes)."""
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if item is target:
                    return f"{node.name}.{target.name}"  # type: ignore[union-attr]
    return target.name  # type: ignore[union-attr]
