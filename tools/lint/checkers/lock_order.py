"""lock-order: the whole-repo lock-acquisition graph must be acyclic,
and the documented kind→commit order must hold as observed edges.

Edges come from two places:

- lexically nested ``with`` regions (``with self._kind_lock(k), self._lock:``
  and the two-statement form both count);
- calls made while holding a lock to same-class methods / same-module
  functions, expanded through a transitive-acquisition fixpoint (so
  ``create()`` holding the kind lock and calling ``self._commit`` —
  which takes ``self._lock`` — yields the kind→commit edge without any
  annotation).

The documented order from the store docstring ("kind lock -> commit
lock, never the reverse") is pinned as :data:`PINNED_EDGES`. A pinned
edge must be OBSERVED (otherwise the pin has rotted and must be
updated), and any cycle — including one a pinned edge participates in,
i.e. somebody acquiring in the reverse order — is a finding that names
the full cycle with one witness site per edge.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from tools.lint.base import Checker, Finding, Module
from tools.lint.locks import ModuleLocks, transitive_locks

# (outer, inner): the order the code must acquire in. Lock ids are
# module-dotted (tfk8s_tpu/ prefix stripped): see tools/lint/locks.py.
PINNED_EDGES: Tuple[Tuple[str, str], ...] = (
    # ClusterStore: per-kind mutation lock, THEN the store-wide commit
    # lock (which _compact_cv aliases). Never the reverse.
    ("client.store.ClusterStore._kind_lock()", "client.store.ClusterStore._lock"),
)


class LockOrderChecker(Checker):
    name = "lock-order"

    def __init__(self, pinned: Optional[Sequence[Tuple[str, str]]] = None):
        self.pinned = tuple(pinned if pinned is not None else PINNED_EDGES)

    def check(self, modules: List[Module]) -> Iterable[Finding]:
        mods = [ModuleLocks(m) for m in modules]
        trans = transitive_locks(mods)

        # edge -> witness (relpath, line, qualname); first witness wins
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

        def add_edge(outer: str, inner: str, rel: str, line: int, qual: str) -> None:
            if outer != inner:
                edges.setdefault((outer, inner), (rel, line, qual))

        for ml in mods:
            rel = ml.module.relpath
            for fn in ml.functions:
                for outer, inner, line in fn.nested:
                    add_edge(outer, inner, rel, line, fn.qualname)
                for call in fn.calls:
                    if not call.held or call.callee is None:
                        continue
                    # resolve same-class / same-module callees only
                    key = None
                    if call.callee.startswith("self.") and fn.cls:
                        meth = call.callee[len("self."):]
                        if "." not in meth:
                            key = (ml.module.dotted, f"{fn.cls}.{meth}")
                    elif "." not in call.callee:
                        key = (ml.module.dotted, call.callee)
                    if key is None or key not in trans:
                        continue
                    for inner in trans[key]:
                        for outer in call.held:
                            add_edge(outer, inner, rel, call.line, fn.qualname)

        # 1. every pinned edge must be observed
        for outer, inner in self.pinned:
            if (outer, inner) not in edges:
                yield Finding(
                    checker=self.name,
                    relpath="tools/lint/checkers/lock_order.py",
                    line=1,
                    qualname="PINNED_EDGES",
                    detail=f"unobserved:{outer}->{inner}",
                    message=(
                        f"pinned lock order {outer} -> {inner} is no longer "
                        f"observed anywhere — the documented order has rotted; "
                        f"update PINNED_EDGES or restore the ordering site"
                    ),
                )

        # 2. the graph (observed edges; pins are a subset once observed)
        #    must be acyclic
        for cycle in _cycles(edges):
            witness_bits = []
            for a, b in zip(cycle, cycle[1:] + cycle[:1]):
                rel, line, qual = edges[(a, b)]
                witness_bits.append(f"{a} -> {b} at {rel}:{line} ({qual})")
            rel, line, qual = edges[(cycle[0], cycle[1] if len(cycle) > 1 else cycle[0])]
            yield Finding(
                checker=self.name,
                relpath=rel,
                line=line,
                qualname=qual,
                detail="cycle:" + "->".join(cycle),
                message=(
                    "lock-order cycle (potential deadlock): "
                    + "; ".join(witness_bits)
                ),
            )


def _cycles(edges: Dict[Tuple[str, str], Tuple[str, int, str]]) -> List[List[str]]:
    """Elementary cycles via DFS back-edge detection, canonicalized
    (rotated to min node, deduped) so each cycle reports once."""
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    for succs in graph.values():
        succs.sort()

    seen_cycles = set()
    out: List[List[str]] = []
    color: Dict[str, int] = {}  # 0 absent, 1 on stack, 2 done
    stack: List[str] = []

    def dfs(node: str) -> None:
        color[node] = 1
        stack.append(node)
        for nxt in graph[node]:
            c = color.get(nxt, 0)
            if c == 0:
                dfs(nxt)
            elif c == 1:
                cyc = stack[stack.index(nxt):]
                i = cyc.index(min(cyc))
                canon = tuple(cyc[i:] + cyc[:i])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    out.append(list(canon))
        stack.pop()
        color[node] = 2

    for node in sorted(graph):
        if color.get(node, 0) == 0:
            dfs(node)
    return out
