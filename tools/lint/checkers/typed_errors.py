"""typed-errors: every ``raise`` on the wire-facing paths — apiserver
request handlers, ServeClient submit, executor/report paths — must be
an exception from the typed taxonomy, because those are the only
classes the transport layers know how to map to status codes
(``_send_store_error``) or to the serve retry contract.

The taxonomy is collected from the tree itself: seed roots
(``StoreError``, ``ServeError``, ``ValidationError``,
``FrozenObjectError``, ``PodDrained``, ``OutOfPages``,
``TopologyError``, ``_AdmissionRejected``) plus every class whose base
chain reaches one of them (so ``DeadlineExceeded(ServeError,
TimeoutError)`` is typed by virtue of the ``ServeError`` base).
``raise e``-style re-raises of caught variables and bare ``raise`` are
always allowed; ``NotImplementedError``/``AssertionError`` are treated
as programmer-contract errors, not wire errors, and allowed. Error
FACTORIES are resolved too: ``raise _map_error(status, ...)`` is fine
because every ``return`` in ``_map_error`` constructs a taxonomy class.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from tools.lint.base import Checker, Finding, Module, QualnameVisitor, dotted_name

# files whose raise sites are reachable from the wire paths
SCOPE = (
    "tfk8s_tpu/client/apiserver.py",
    "tfk8s_tpu/client/remote.py",
    "tfk8s_tpu/client/store.py",
    "tfk8s_tpu/runtime/server.py",
    "tfk8s_tpu/runtime/registry.py",
    "tfk8s_tpu/runtime/paging.py",
    "tfk8s_tpu/runtime/handoff.py",
    "tfk8s_tpu/runtime/sched/scheduler.py",
    "tfk8s_tpu/runtime/sched/speculative.py",
    # the KV economy (ISSUE 17): every tier failure must surface as
    # HandoffError so the promote path can degrade to plain prefill
    "tfk8s_tpu/runtime/kvtier/__init__.py",
    "tfk8s_tpu/runtime/kvtier/host.py",
    "tfk8s_tpu/runtime/kvtier/peer.py",
    "tfk8s_tpu/runtime/kvtier/directory.py",
    "tfk8s_tpu/gateway/server.py",
    "tfk8s_tpu/gateway/affinity.py",
    "tfk8s_tpu/gateway/router.py",
    "tfk8s_tpu/gateway/admission.py",
    "tfk8s_tpu/gateway/client.py",
    "tfk8s_tpu/gateway/health.py",
)

SEED_ROOTS = {
    "StoreError", "ServeError", "ValidationError", "FrozenObjectError",
    "PodDrained", "OutOfPages", "TopologyError", "_AdmissionRejected",
    # the KV handoff plane's typed wire error (runtime/handoff.py): a
    # standalone root — deriving from ServeError would cycle the import
    "HandoffError",
}
# contract violations by the CALLER'S programmer, not wire errors
CONTRACT_ERRORS = {"NotImplementedError", "AssertionError", "StopIteration"}


def collect_taxonomy(modules: List[Module]) -> Set[str]:
    """Seed roots + every class transitively deriving from one,
    anywhere in the linted tree."""
    bases = {}
    for module in modules:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                base_names = []
                for b in node.bases:
                    name = dotted_name(b)
                    if name:
                        base_names.append(name.rsplit(".", 1)[-1])
                bases.setdefault(node.name, set()).update(base_names)
    allowed = set(SEED_ROOTS)
    changed = True
    while changed:
        changed = False
        for cls, cls_bases in bases.items():
            if cls not in allowed and cls_bases & allowed:
                allowed.add(cls)
                changed = True

    # error factories: a function is as typed as its returns — if every
    # `return` constructs an allowed class, raising the factory's result
    # is allowed (fixpoint so factories may call factories)
    returns = {}
    for module in modules:
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            rets = []
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return) and sub.value is not None:
                    callee = (
                        dotted_name(sub.value.func)
                        if isinstance(sub.value, ast.Call) else None
                    )
                    rets.append(callee.rsplit(".", 1)[-1] if callee else None)
            if rets and all(r is not None for r in rets):
                returns[node.name] = set(rets)
    changed = True
    while changed:
        changed = False
        for fn, ret_names in returns.items():
            if fn not in allowed and ret_names <= allowed:
                allowed.add(fn)
                changed = True
    return allowed


class _RaiseVisitor(QualnameVisitor):
    def __init__(self, checker: "TypedErrorsChecker", module: Module,
                 allowed: Set[str]):
        super().__init__()
        self.checker = checker
        self.module = module
        self.allowed = allowed
        self.findings: List[Finding] = []

    def visit_Raise(self, node: ast.Raise) -> None:
        exc = node.exc
        name = None
        if exc is None:
            pass  # bare re-raise
        elif isinstance(exc, ast.Call):
            callee = dotted_name(exc.func)
            name = callee.rsplit(".", 1)[-1] if callee else None
        elif isinstance(exc, ast.Name):
            # `raise err` re-raise of a variable vs `raise ValueError`
            name = exc.id if exc.id[:1].isupper() else None
        elif isinstance(exc, ast.Attribute):
            name = exc.attr if exc.attr[:1].isupper() else None
        if (
            name is not None
            and name not in self.allowed
            and name not in CONTRACT_ERRORS
        ):
            self.findings.append(Finding(
                checker=self.checker.name,
                relpath=self.module.relpath,
                line=node.lineno,
                qualname=self.qualname,
                detail=f"raise:{name}",
                message=(
                    f"raise {name} on a wire-facing path — use a class from "
                    f"the typed taxonomy (StoreError/ServeError/... tree) so "
                    f"transports can map it"
                ),
            ))
        self.generic_visit(node)


class TypedErrorsChecker(Checker):
    name = "typed-errors"

    def __init__(self, scope=SCOPE):
        self.scope = tuple(scope)

    def relevant(self, relpath: str) -> bool:
        # taxonomy collection needs the whole package; raise-site
        # scoping to self.scope happens in check()
        return relpath.startswith("tfk8s_tpu/")

    def check(self, modules: List[Module]) -> Iterable[Finding]:
        allowed = collect_taxonomy(modules)
        for module in modules:
            if module.relpath not in self.scope:
                continue
            visitor = _RaiseVisitor(self, module, allowed)
            visitor.visit(module.tree)
            yield from visitor.findings
