"""metric-names: the /metrics namespace rules, folded into the lint
framework from ``tools/check_metric_names.py`` (which stays importable
and standalone-runnable — tests/test_metric_names.py pins its API).

The RULES live in one place — this checker imports the regexes and
sanitize/suffix logic from ``tools.check_metric_names`` and only adapts
the scan loop to produce keyed :class:`Finding`\\ s, so the wanted-set
tests and this checker can never disagree about what a valid name is.
"""

from __future__ import annotations

from typing import Iterable, List

from tools.check_metric_names import (
    _CALL_RE,
    _EXPOSED_NAME_RE,
    _HIST_SUFFIXES,
    _PLACEHOLDER_RE,
    _sanitize,
)
from tools.lint.base import Checker, Finding, Module

_SELF = "tools/check_metric_names.py"  # its docstring shows bad examples


class MetricNamesChecker(Checker):
    name = "metric-names"

    def relevant(self, relpath: str) -> bool:
        if relpath == _SELF:
            return False
        return (
            relpath.startswith(("tfk8s_tpu/", "tools/"))
            or relpath == "bench.py"
        )

    def check(self, modules: List[Module]) -> Iterable[Finding]:
        for module in modules:
            src = module.source
            for m in _CALL_RE.finditer(src):
                verb, raw = m.group("verb"), m.group("name")
                line = src.count("\n", 0, m.start()) + 1
                exposed = _sanitize(
                    _PLACEHOLDER_RE.sub("x", raw) if m.group("fprefix") else raw
                )
                problem = None
                if not _EXPOSED_NAME_RE.match(exposed):
                    problem = f"exposes {exposed!r} — not snake_case"
                elif verb == "inc" and not exposed.endswith("_total"):
                    problem = "counter must end in _total"
                elif verb == "observe" and not exposed.endswith(_HIST_SUFFIXES):
                    problem = (
                        "histogram must end in one of "
                        + "/".join(_HIST_SUFFIXES)
                    )
                if problem is not None:
                    yield Finding(
                        checker=self.name,
                        relpath=module.relpath,
                        line=line,
                        qualname="",
                        detail=f"{verb}:{raw}",
                        message=f"{verb}({raw!r}): {problem}",
                    )
