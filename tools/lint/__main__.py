"""``python -m tools.lint`` — the single entry point.

Exit status: 0 when clean, 1 on findings / format errors / unused
suppressions. ``--no-suppress`` shows everything the checkers see
(useful when triaging); ``--checker NAME`` (repeatable) runs a subset.
"""

from __future__ import annotations

import argparse
import sys
from typing import List

from tools.lint.checkers import all_checkers
from tools.lint.driver import run_lint


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="tfk8s-lint: repo-native static analysis",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs (default: repo scope)")
    ap.add_argument("--no-suppress", action="store_true",
                    help="ignore suppressions.txt (triage mode)")
    ap.add_argument("--checker", action="append", default=[],
                    help="run only this checker (repeatable)")
    args = ap.parse_args(argv)

    checkers = None
    if args.checker:
        by_name = {c.name: c for c in all_checkers()}
        unknown = [n for n in args.checker if n not in by_name]
        if unknown:
            print(f"unknown checker(s): {', '.join(unknown)}; "
                  f"have: {', '.join(sorted(by_name))}", file=sys.stderr)
            return 2
        checkers = [by_name[n] for n in args.checker]

    result = run_lint(
        paths=args.paths or None,
        checkers=checkers,
        suppress=not args.no_suppress,
    )
    for err in result.errors:
        print(f"ERROR: {err}")
    for finding in result.findings:
        print(finding.render())
    for sup in result.unused_suppressions:
        print(f"suppressions.txt:{sup.lineno}: UNUSED suppression "
              f"{sup.pattern!r} — delete it")
    n_checkers = len(checkers) if checkers is not None else len(all_checkers())
    if result.clean:
        print(f"lint ok ({n_checkers} checkers, "
              f"{len(result.suppressed)} suppressed with reason)")
        return 0
    print(f"{len(result.findings)} finding(s), {len(result.errors)} error(s), "
          f"{len(result.unused_suppressions)} unused suppression(s)")
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
