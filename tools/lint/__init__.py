"""tfk8s-lint: the repo-native static analysis suite (ISSUE 9).

One shared AST driver (:mod:`tools.lint.driver`), per-checker modules
under :mod:`tools.lint.checkers`, one triaged suppressions file
(``tools/lint/suppressions.txt``), one entry point::

    python -m tools.lint            # lint the default scope, exit 1 on findings
    python -m tools.lint path/...   # lint specific files/dirs

The six checkers turn the concurrency/purity invariants that previously
lived in CHANGES.md prose into machine-checked properties:

==================== ======================================================
checker              invariant
==================== ======================================================
lock-order           the whole-repo lock-acquisition graph is acyclic and
                     the documented kind→commit order holds (pinned edges)
blocking-under-lock  no sleep / file・socket IO / unbounded join / foreign
                     condition-wait / jit dispatch inside a held lock
frozen-mutation      objects from store/informer/lister read paths are
                     never written without an intervening thaw()/deepcopy
typed-errors         every raise on apiserver handler, ServeClient submit,
                     and executor report paths is from the typed taxonomy
seeded-determinism   no wall-clock/module-state RNG inside the seeded
                     augmentation/chaos/checkpoint-discovery paths
metric-names         the /metrics namespace rules (snake_case, _total,
                     unit suffixes) — folded in from check_metric_names
==================== ======================================================

Suppressions are keyed ``checker:relpath:qualname:detail`` (fnmatch
globs allowed per field) and MUST carry a reason — an unexplained
suppression is itself a lint error, and unused suppressions are reported
so the file can only shrink as code improves. Wired into tier-1 by
``tests/test_lint.py`` (in-process, no subprocess-per-checker).
"""

from tools.lint.base import Checker, Finding, Module, Suppression  # noqa: F401
from tools.lint.driver import (  # noqa: F401
    DEFAULT_SUPPRESSIONS,
    default_paths,
    load_suppressions,
    run_lint,
)
