"""Shared checker plumbing: parsed modules, findings, suppressions.

A checker sees :class:`Module` objects (path + parsed AST + source) and
yields :class:`Finding`\\ s. Findings carry a stable ``key`` —
``checker:relpath:qualname:detail`` — that survives line-number drift,
so the suppressions file does not rot every time an unrelated edit moves
code around. Line numbers are for humans only.
"""

from __future__ import annotations

import ast
import fnmatch
from dataclasses import dataclass, field
from typing import Iterable, List, Optional


@dataclass
class Module:
    """One parsed source file handed to every checker."""

    path: str  # absolute
    relpath: str  # repo-relative, '/'-separated (the key form)
    tree: ast.Module
    source: str

    @property
    def dotted(self) -> str:
        """``tfk8s_tpu/client/store.py`` → ``client.store`` (the lock-name
        prefix; top-level files keep their stem)."""
        rel = self.relpath
        for prefix in ("tfk8s_tpu/", "tools/", "tests/"):
            if rel.startswith(prefix):
                rel = rel[len(prefix):]
                break
        return rel[:-3].replace("/", ".") if rel.endswith(".py") else rel


@dataclass
class Finding:
    checker: str
    relpath: str
    line: int
    qualname: str  # enclosing Class.method / function ('' at module level)
    detail: str  # what was matched (lock pair, callee, exception name...)
    message: str

    @property
    def key(self) -> str:
        return f"{self.checker}:{self.relpath}:{self.qualname}:{self.detail}"

    def render(self) -> str:
        return (
            f"{self.relpath}:{self.line}: [{self.checker}] {self.message}\n"
            f"    key: {self.key}"
        )


@dataclass
class Suppression:
    """One triaged line of ``suppressions.txt``: a key pattern (fnmatch
    globs allowed in every field) plus the mandatory reason."""

    pattern: str
    reason: str
    lineno: int
    used: bool = field(default=False)

    def matches(self, finding_key: str) -> bool:
        return fnmatch.fnmatchcase(finding_key, self.pattern)


class Checker:
    """Base class: subclasses set ``name`` and implement :meth:`check`.

    ``relevant`` scopes which files a checker sees — the driver parses
    the union of all scopes once and fans the modules out.
    """

    name: str = ""

    def relevant(self, relpath: str) -> bool:
        return relpath.startswith("tfk8s_tpu/")

    def check(self, modules: List[Module]) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


class QualnameVisitor(ast.NodeVisitor):
    """Visitor that tracks the enclosing ``Class.method`` qualname —
    the shared scaffolding every AST checker builds on."""

    def __init__(self) -> None:
        self._stack: List[str] = []

    @property
    def qualname(self) -> str:
        return ".".join(self._stack)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None. Call roots are
    resolved through to their func (``self.f(x).g`` → ``self.f().g``)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    if isinstance(node, ast.Call):
        base = dotted_name(node.func)
        return f"{base}()" if base else None
    return None
