"""Lock-region extraction shared by the lock-order and
blocking-under-lock checkers.

What counts as a lock acquisition (the repo's idioms, all of them
``with``-based — bare ``.acquire()`` is not used and stays un-modeled):

- ``with self._lock:`` / ``with self._cond:`` — an instance attribute
  whose final name segment is lock-ish (``lock``/``cond``/``mutex``/
  ``cv``, optionally underscore-prefixed, any case);
- ``with self._kind_lock(kind):`` — a lock-returning method (same
  name rule), identified per METHOD, not per returned instance: the
  kind-lock family is one rung in the documented order;
- ``with _metrics_lock:`` — a module-global lock name.

Lock identity is ``<module-dotted>.<Class>.<attr>`` (or ``...<meth>()``
for lock factories, ``<module-dotted>.<name>`` for globals).
``threading.Condition(self._lock)`` aliases the condition attribute to
the lock it wraps, so waiting on the condition is recognized as using
the same underlying lock (the store's ``_compact_cv``).

The analysis is intentionally lexical-plus-one-hop: nested ``with``
regions give direct edges, and calls to methods of the SAME class (or
functions of the same module) made while holding a lock contribute the
callee's transitively-acquired locks as edges. Cross-object attribute
calls are not resolved — that keeps the graph sound on the idioms the
repo actually uses without a type system.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.lint.base import Module, dotted_name

_LOCKISH = re.compile(r"(?:^|_)(?:lock|locks?|cond|mutex|cv)$", re.IGNORECASE)


def is_lockish_name(name: str) -> bool:
    return bool(_LOCKISH.search(name))


@dataclass
class Acquisition:
    """One ``with <lock>`` region."""

    lock: str  # canonical lock id (alias-resolved)
    node: ast.With  # the with statement
    body: List[ast.stmt]
    line: int


@dataclass
class CallSite:
    callee: Optional[str]  # dotted callee ('self.f', 'mod.f', 'f', ...)
    node: ast.Call
    held: Tuple[str, ...]  # locks held (outermost first), deduped
    line: int


@dataclass
class FunctionInfo:
    module: Module
    qualname: str  # Class.method or function name
    cls: Optional[str]
    node: ast.AST
    acquisitions: List[Acquisition] = field(default_factory=list)
    # every call in the body, with the lock stack held at that point
    calls: List[CallSite] = field(default_factory=list)
    # (outer, inner, line) for lexically nested with-lock pairs
    nested: List[Tuple[str, str, int]] = field(default_factory=list)
    # locks acquired anywhere in this function, directly
    direct_locks: Set[str] = field(default_factory=set)
    # names of same-class methods / same-module functions called anywhere
    local_callees: Set[str] = field(default_factory=set)


def _class_aliases(cls: ast.ClassDef) -> Dict[str, str]:
    """``self.X = threading.Condition(self.Y)`` → {X: Y} (anywhere in the
    class body; in practice ``__init__``)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt, val = node.targets[0], node.value
        if not (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"
            and isinstance(val, ast.Call)
        ):
            continue
        callee = dotted_name(val.func)
        if callee in ("threading.Condition", "Condition") and val.args:
            src = val.args[0]
            if (
                isinstance(src, ast.Attribute)
                and isinstance(src.value, ast.Name)
                and src.value.id == "self"
            ):
                aliases[tgt.attr] = src.attr
    return aliases


class ModuleLocks:
    """All lock-relevant facts of one module."""

    def __init__(self, module: Module):
        self.module = module
        self.functions: List[FunctionInfo] = []
        self._aliases: Dict[str, Dict[str, str]] = {}  # class -> attr alias map
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                self._aliases[node.name] = _class_aliases(node)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._scan_function(item, cls=node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(node, cls=None)

    # -- lock identification ------------------------------------------------

    def lock_id(self, expr: ast.AST, cls: Optional[str]) -> Optional[str]:
        """Canonical lock id for a with-item / wait-receiver expression,
        or None when it isn't lock-shaped."""
        mod = self.module.dotted
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and cls is not None:
                attr = expr.attr
                seen = set()
                while attr in self._aliases.get(cls, {}) and attr not in seen:
                    seen.add(attr)
                    attr = self._aliases[cls][attr]
                if is_lockish_name(attr):
                    return f"{mod}.{cls}.{attr}"
                return None
        if isinstance(expr, ast.Call):
            callee = expr.func
            if (
                isinstance(callee, ast.Attribute)
                and isinstance(callee.value, ast.Name)
                and callee.value.id == "self"
                and cls is not None
                and is_lockish_name(callee.attr)
            ):
                return f"{mod}.{cls}.{callee.attr}()"
            return None
        if isinstance(expr, ast.Name) and is_lockish_name(expr.id):
            return f"{mod}.{expr.id}"
        return None

    # -- per-function scan ----------------------------------------------------

    def _scan_function(self, fn: ast.AST, cls: Optional[str]) -> None:
        qual = f"{cls}.{fn.name}" if cls else fn.name
        info = FunctionInfo(module=self.module, qualname=qual, cls=cls, node=fn)
        self._walk(fn.body, info, held=[])
        self.functions.append(info)

    def _walk(self, stmts: List[ast.stmt], info: FunctionInfo, held: List[str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs run later, not under this lock
            if isinstance(stmt, ast.With):
                locks_here: List[str] = []
                for item in stmt.items:
                    lock = self.lock_id(item.context_expr, info.cls)
                    if lock is not None:
                        info.direct_locks.add(lock)
                        for outer in held + locks_here:
                            if outer != lock:
                                info.nested.append((outer, lock, stmt.lineno))
                        locks_here.append(lock)
                        info.acquisitions.append(
                            Acquisition(
                                lock=lock, node=stmt, body=stmt.body,
                                line=stmt.lineno,
                            )
                        )
                    else:
                        # the with-item EXPRESSION evaluates before any
                        # acquisition in this statement (open(...) etc.)
                        self._scan_calls(item.context_expr, info, held)
                self._walk(stmt.body, info, held + locks_here)
                continue
            # every other compound statement: collect calls in the
            # non-body expressions, then recurse into bodies in order
            for child_body in _stmt_bodies(stmt):
                self._walk(child_body, info, held)
            for expr in _stmt_exprs(stmt):
                self._scan_calls(expr, info, held)

    def _scan_calls(self, expr: ast.AST, info: FunctionInfo, held: List[str]) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            dedup: Tuple[str, ...] = tuple(dict.fromkeys(held))
            info.calls.append(
                CallSite(callee=callee, node=node, held=dedup, line=node.lineno)
            )
            if callee is not None:
                if callee.startswith("self."):
                    parts = callee.split(".")
                    if len(parts) == 2:
                        info.local_callees.add(parts[1])
                elif "." not in callee:
                    info.local_callees.add(callee)


def _stmt_bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
    out = []
    for name in ("body", "orelse", "finalbody"):
        b = getattr(stmt, name, None)
        if isinstance(b, list) and b and isinstance(b[0], ast.stmt):
            out.append(b)
    for h in getattr(stmt, "handlers", []) or []:
        out.append(h.body)
    return out


def _stmt_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expression children of a statement that are NOT nested
    statement bodies (test/iter/targets/value...)."""
    out: List[ast.AST] = []
    for field_name, value in ast.iter_fields(stmt):
        if field_name in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(value, ast.AST):
            out.append(value)
        elif isinstance(value, list):
            out.extend(v for v in value if isinstance(v, ast.AST) and not isinstance(v, ast.stmt))
    return out


def transitive_locks(mods: List[ModuleLocks]) -> Dict[Tuple[str, str], Set[str]]:
    """(module.dotted, qualname) → every lock the function may acquire,
    including through same-class / same-module calls (fixpoint)."""
    by_key: Dict[Tuple[str, str], FunctionInfo] = {}
    for ml in mods:
        for fn in ml.functions:
            by_key[(ml.module.dotted, fn.qualname)] = fn
    acq: Dict[Tuple[str, str], Set[str]] = {
        k: set(fn.direct_locks) for k, fn in by_key.items()
    }
    changed = True
    while changed:
        changed = False
        for key, fn in by_key.items():
            mod = key[0]
            for callee in fn.local_callees:
                for target in (
                    (mod, f"{fn.cls}.{callee}") if fn.cls else None,
                    (mod, callee),
                ):
                    if target and target in acq:
                        before = len(acq[key])
                        acq[key] |= acq[target]
                        if len(acq[key]) != before:
                            changed = True
    return acq
