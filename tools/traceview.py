"""traceview — render one request's trace from a JSONL span export.

The serving plane writes spans as JSON lines (``Tracer.export_jsonl`` /
the ``/traces`` endpoint piped through ``jq -c '.[] | .spans[]'``); this
tool turns one trace back into the thing an engineer actually wants at
3am: the span TREE (who called whom, where the time went) and the token
TIMELINE of the decode loop (admission wait, prefill, TTFT, the TPOT
samples, why the request retired).

Usage::

    python tools/traceview.py spans.jsonl                 # slowest request
    python tools/traceview.py spans.jsonl --trace-id <id> # that one
    python tools/traceview.py spans.jsonl --list          # trace index

With no ``--trace-id`` the tool picks the SLOWEST request trace in the
file (longest root-span duration) — tail sampling keeps exactly the
traces worth reading, and the slowest kept one is where an investigation
starts. Exit code: 0 on a rendered trace, 1 on no match/empty file.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

Span = Dict[str, Any]


def load_spans(path: str) -> List[Span]:
    """Parse one span dict per line; blank/corrupt lines are skipped
    (a live exporter may be appending mid-line at read time)."""
    spans: List[Span] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if isinstance(d, dict) and "trace_id" in d and "span_id" in d:
                spans.append(d)
    return spans


def group_traces(spans: List[Span]) -> Dict[str, List[Span]]:
    by_trace: Dict[str, List[Span]] = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    for group in by_trace.values():
        group.sort(key=lambda s: s.get("start_time") or 0.0)
    return by_trace


def _roots(group: List[Span]) -> List[Span]:
    ids = {s["span_id"] for s in group}
    return [s for s in group if s.get("parent_id") not in ids]


def _duration(s: Span) -> float:
    d = s.get("duration_s")
    return float(d) if d is not None else 0.0


def pick_slowest(by_trace: Dict[str, List[Span]]) -> Optional[str]:
    """The trace whose slowest root span is longest — for request traces
    that root is the client/gateway span, i.e. end-to-end latency."""
    best, best_d = None, -1.0
    for tid, group in by_trace.items():
        d = max((_duration(r) for r in _roots(group)), default=0.0)
        if d > best_d:
            best, best_d = tid, d
    return best


def _fmt_ms(seconds: Optional[float]) -> str:
    return "   ?   " if seconds is None else f"{seconds * 1000.0:8.2f}ms"


def _fmt_attrs(attrs: Dict[str, Any]) -> str:
    if not attrs:
        return ""
    inner = ", ".join(f"{k}={attrs[k]}" for k in sorted(attrs))
    return f"  [{inner}]"


def _render_events(span: Span, indent: str, out: List[str]) -> None:
    events = span.get("events") or []
    if not events:
        return
    t0 = span.get("start_time") or 0.0
    for e in events:
        off = (e.get("ts") or t0) - t0
        out.append(
            f"{indent}  @ {off * 1000.0:8.2f}ms {e.get('name', '?')}"
            f"{_fmt_attrs(e.get('attributes') or {})}"
        )


def render_tree(group: List[Span]) -> List[str]:
    """Indented span tree, children under parents in start order; spans
    whose parent never made it into the export surface as extra roots
    rather than vanishing."""
    children: Dict[str, List[Span]] = {}
    for s in group:
        children.setdefault(s.get("parent_id") or "", []).append(s)
    out: List[str] = []

    def walk(span: Span, depth: int) -> None:
        indent = "  " * depth
        status = span.get("status", "ok")
        flag = "" if status == "ok" else f"  !{status}: {span.get('message', '')}"
        out.append(
            f"{indent}{'└─ ' if depth else ''}{span['name']}"
            f"  {_fmt_ms(span.get('duration_s'))}"
            f"{_fmt_attrs(span.get('attributes') or {})}{flag}"
        )
        _render_events(span, indent + ("   " if depth else ""), out)
        for child in children.get(span["span_id"], []):
            walk(child, depth + 1)

    for root in _roots(group):
        walk(root, 0)
    return out


def render_token_timeline(group: List[Span]) -> List[str]:
    """The decode-loop view: for each ``serve.request`` span, the token
    events as a sparkline-ish table — TTFT first, then the (strided)
    TPOT samples, then the retirement reason."""
    out: List[str] = []
    for span in group:
        if span.get("name") != "serve.request":
            continue
        events = span.get("events") or []
        ttft = next(
            (e for e in events if e.get("name") == "first_token"), None
        )
        tokens = [e for e in events if e.get("name") == "token"]
        retire = next((e for e in events if e.get("name") == "retire"), None)
        attrs = span.get("attributes") or {}
        out.append(
            f"token timeline ({attrs.get('tokens_out', '?')} tokens, "
            f"prefix-cache {attrs.get('cached_pages', 0)} page(s), "
            f"{attrs.get('prefill_chunks', 0)} prefill chunk(s)):"
        )
        if ttft is not None:
            a = ttft.get("attributes") or {}
            out.append(f"  ttft  {float(a.get('ttft_s', 0.0)) * 1000.0:8.2f}ms")
        for e in tokens:
            a = e.get("attributes") or {}
            out.append(
                f"  tok {int(a.get('i', 0)):4d}  "
                f"tpot {float(a.get('tpot_s', 0.0)) * 1000.0:7.3f}ms"
            )
        if retire is not None:
            a = retire.get("attributes") or {}
            out.append(
                f"  retired: {a.get('reason', '?')} "
                f"after {a.get('tokens', '?')} token(s)"
            )
    return out


def render_trace(trace_id: str, group: List[Span]) -> str:
    total = max((_duration(r) for r in _roots(group)), default=0.0)
    lines = [
        f"trace {trace_id}  ({len(group)} span(s), {total * 1000.0:.2f}ms)"
    ]
    lines.extend(render_tree(group))
    timeline = render_token_timeline(group)
    if timeline:
        lines.append("")
        lines.extend(timeline)
    return "\n".join(lines)


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="traceview", description=__doc__.splitlines()[0]
    )
    ap.add_argument("path", help="JSONL span export (one span per line)")
    ap.add_argument("--trace-id", help="render this trace (default: slowest)")
    ap.add_argument(
        "--list", action="store_true",
        help="index of traces in the file, slowest first",
    )
    args = ap.parse_args(argv)

    by_trace = group_traces(load_spans(args.path))
    if not by_trace:
        print("no spans found", file=sys.stderr)
        return 1

    if args.list:
        rows = sorted(
            by_trace.items(),
            key=lambda kv: -max((_duration(r) for r in _roots(kv[1])), default=0.0),
        )
        for tid, group in rows:
            d = max((_duration(r) for r in _roots(group)), default=0.0)
            root = _roots(group)[0]["name"] if _roots(group) else "?"
            print(f"{tid}  {d * 1000.0:8.2f}ms  {len(group):3d} span(s)  {root}")
        return 0

    tid = args.trace_id or pick_slowest(by_trace)
    if tid not in by_trace:
        print(f"trace {tid!r} not in file", file=sys.stderr)
        return 1
    print(render_trace(tid, by_trace[tid]))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
