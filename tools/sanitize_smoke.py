"""Malformed-input smoke corpus for the sanitized native cores.

Run INSIDE the sanitizer environment (tests/test_sanitizers.py is the
harness that sets it up)::

    TFK8S_NATIVE_SANITIZE=ubsan python -m tools.sanitize_smoke
    TFK8S_NATIVE_SANITIZE=asan LD_PRELOAD=$(gcc -print-file-name=libasan.so) \\
        ASAN_OPTIONS=detect_leaks=0 python -m tools.sanitize_smoke

The corpus is generated, not checked in, and fully deterministic: a
valid record shard / JPEG, then systematic truncations, bit flips, a
lying length field, a lying geometry stamp, and pure garbage. Every
input is driven through the native entry points (``rio_index`` /
``rio_read`` via RecordFile, ``img_info`` / ``img_decode_scaled`` /
``img_decode_rrc`` via the binder). The CONTRACT under test: malformed
bytes produce a typed refusal (RecordIOError / None / False), never a
sanitizer report — asan/ubsan turn any out-of-bounds parse into a
process abort, which the harness surfaces with the sanitizer's own
stack trace.

Exit 0: corpus survived. Exit 1: a core accepted what it should have
refused, or refused what it must accept. Sanitizer aborts exit with the
sanitizer's status and report.
"""

from __future__ import annotations

import argparse
import os
import struct
import sys
import tempfile
import traceback
from typing import Callable, List


def _mutations(valid: bytes) -> List[bytes]:
    """The shared corpus shape: truncations sweeping the whole file,
    single-bit flips sweeping header and tail regions, and garbage."""
    out: List[bytes] = []
    step = max(1, len(valid) // 64)
    out.extend(valid[:n] for n in range(0, len(valid), step))
    for pos in list(range(0, min(64, len(valid)))) + list(
        range(max(0, len(valid) - 16), len(valid))
    ):
        flipped = bytearray(valid)
        flipped[pos] ^= 0x40
        out.append(bytes(flipped))
    out.append(b"")
    out.append(b"\xff" * 257)
    out.append(bytes(range(256)) * 5)
    return out


def smoke_recordio(tmp: str) -> int:
    from tfk8s_tpu.data import _native
    from tfk8s_tpu.data.recordio import RecordFile, RecordIOError, RecordWriter

    if _native.load() is None:
        print("recordio: native core not loaded — nothing to smoke")
        return 0

    shard = os.path.join(tmp, "valid.rio")
    with RecordWriter(shard) as w:
        for i in range(32):
            w.write(bytes([i]) * (i * 7 + 1))
    valid = open(shard, "rb").read()

    corpus = _mutations(valid)
    # a lying length field: claims a record body far past EOF
    lying = bytearray(valid)
    huge = struct.pack("<Q", 2**40)
    lying[0:8] = huge
    corpus.append(bytes(lying))

    failures = 0
    for i, blob in enumerate(corpus):
        path = os.path.join(tmp, "case.rio")
        with open(path, "wb") as f:
            f.write(blob)
        try:
            rf = RecordFile(path)
            rf.read(range(len(rf)), verify=True)
        except RecordIOError:
            pass  # the typed refusal — exactly the contract
        except Exception:
            print(f"recordio case {i} ({len(blob)} bytes): WRONG error type")
            traceback.print_exc()
            failures += 1
    # and the valid shard must still round-trip
    rf = RecordFile(shard)
    got = rf.read(range(len(rf)))
    want = [bytes([i]) * (i * 7 + 1) for i in range(32)]
    if got != want:
        print("recordio: valid shard did not round-trip under sanitizer")
        failures += 1
    print(f"recordio: {len(corpus)} corpus cases, {failures} failure(s)")
    return failures


def smoke_imagecore(tmp: str) -> int:
    import numpy as np

    from tfk8s_tpu.data.images import _native_decode as nd

    if nd.load() is None:
        print("imagecore: native core not loaded — nothing to smoke")
        return 0

    try:
        from PIL import Image
    except ImportError:
        print("imagecore: PIL unavailable — cannot generate the corpus")
        return 0

    # deterministic gradient frame -> real JPEG bytes
    h, w = 97, 131
    y, x = np.mgrid[0:h, 0:w]
    frame = np.stack(
        [(x * 2) % 256, (y * 3) % 256, (x + y) % 256], axis=-1
    ).astype(np.uint8)
    jpg_path = os.path.join(tmp, "valid.jpg")
    Image.fromarray(frame).save(jpg_path, "JPEG", quality=90)
    valid = open(jpg_path, "rb").read()

    scale = np.ones(3, np.float32)
    bias = np.zeros(3, np.float32)

    def drive(blob: bytes, stamp=(h, w)) -> None:
        nd.jpeg_info(blob)
        for s in (8, 4, 3, 1):
            nd.decode_jpeg_scaled(blob, s)
        dst = np.empty((32, 32, 3), np.float32)
        nd.decode_rrc_into(
            blob, (5, 5, 48, 48), 32, True, 8, scale, bias, dst, stamp
        )

    failures = 0
    corpus = _mutations(valid)
    for i, blob in enumerate(corpus):
        try:
            drive(blob)
        except Exception:
            print(f"imagecore case {i} ({len(blob)} bytes): unexpected raise")
            traceback.print_exc()
            failures += 1
    # the lying-geometry stamp: header says 97x131, caller claims a tiny
    # frame (undersized scratch) and a huge one — both must be refusals
    # or correct decodes, never a scratch overflow
    for stamp in ((8, 8), (4000, 4000)):
        try:
            drive(valid, stamp=stamp)
        except Exception:
            print(f"imagecore lying stamp {stamp}: unexpected raise")
            traceback.print_exc()
            failures += 1
    # and the valid image must still decode
    out = nd.decode_jpeg(valid)
    if out is None or out.shape != (h, w, 3):
        print("imagecore: valid JPEG no longer decodes under sanitizer")
        failures += 1
    print(f"imagecore: {len(corpus) + 2} corpus cases, {failures} failure(s)")
    return failures


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.sanitize_smoke")
    ap.add_argument("--core", choices=["recordio", "imagecore", "all"],
                    default="all")
    args = ap.parse_args(argv)

    from tfk8s_tpu.data import _native

    if _native.sanitize_mode() is None:
        print("refusing to run: set TFK8S_NATIVE_SANITIZE=asan|ubsan "
              "(an unsanitized smoke run proves nothing)", file=sys.stderr)
        return 2

    cores: List[Callable[[str], int]] = []
    if args.core in ("recordio", "all"):
        cores.append(smoke_recordio)
    if args.core in ("imagecore", "all"):
        cores.append(smoke_imagecore)

    failures = 0
    with tempfile.TemporaryDirectory(prefix="tfk8s-sanitize-") as tmp:
        for core in cores:
            failures += core(tmp)
    print("sanitize smoke:", "FAIL" if failures else "ok")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
