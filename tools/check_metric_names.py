"""Metric-name lint: keep the /metrics namespace coherent as it grows.

Statically scans Python sources for registry calls (``metrics.inc(...)``,
``.observe(...)``, ``.set_gauge(...)``, ``.describe(...)``) and validates
every literal metric name against the conventions the build exposes on
/metrics (Prometheus naming + unit-suffix rules):

- the EXPOSED name (dots/dashes sanitize to underscores, see
  utils/logging.py) must be snake_case: ``[a-z_][a-z0-9_]*``; no
  uppercase, no digits-first, nothing that needs further mangling;
- counters (``inc``) must end in ``_total`` — the Prometheus counter
  convention that makes rate() targets self-describing;
- histograms (``observe``) must carry a unit suffix: ``_seconds`` or
  ``_bytes``;
- f-string name segments are allowed for registry prefixes (e.g.
  ``f"{self.name}.syncs_total"``); each ``{...}`` placeholder is treated
  as an opaque snake_case atom, so the surrounding literal text still
  lints. Dynamic identity belongs in LABELS, not in the name — which is
  why a placeholder in the FINAL name segment of a counter/histogram
  still has to satisfy the suffix rule through the literal tail.

Beyond the static source scan, ``lint_exposition`` validates rendered
/metrics text — every sample line must parse, and the OpenMetrics-style
exemplar suffix (`` # {trace_id="..."} <value>``, emitted by
``Metrics.observe(..., exemplar=...)``) is legal ONLY on ``_bucket``
lines: exemplars anchor a histogram observation to the trace that
produced it, and nothing else carries one.

Wired into the tier-1 suite by tests/test_metric_names.py; also runnable
standalone: ``python tools/check_metric_names.py [paths...]`` exits 1 and
prints one line per violation.
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

# metrics.inc("name"...) / self.metrics.observe(f"...")/ m.set_gauge('x')
_CALL_RE = re.compile(
    r"""\.(?P<verb>inc|observe|set_gauge|describe)\(\s*
        (?P<fprefix>f?)(?P<quote>['"])(?P<name>[^'"]+)(?P=quote)""",
    re.VERBOSE,
)
_PLACEHOLDER_RE = re.compile(r"\{[^}]*\}")
_EXPOSED_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

_HIST_SUFFIXES = ("_seconds", "_bytes")


def _sanitize(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


def lint_source(path: str, source: str) -> List[str]:
    problems: List[str] = []
    for m in _CALL_RE.finditer(source):
        verb, raw = m.group("verb"), m.group("name")
        line = source.count("\n", 0, m.start()) + 1
        where = f"{path}:{line}"
        name = raw
        if m.group("fprefix"):
            # each interpolated segment is an opaque snake_case atom
            name = _PLACEHOLDER_RE.sub("x", name)
        exposed = _sanitize(name)
        if not _EXPOSED_NAME_RE.match(exposed):
            problems.append(
                f"{where}: {verb}({raw!r}) exposes {exposed!r} — not snake_case"
            )
            continue
        if verb == "inc" and not exposed.endswith("_total"):
            problems.append(
                f"{where}: counter {raw!r} must end in _total"
            )
        if verb == "observe" and not exposed.endswith(_HIST_SUFFIXES):
            problems.append(
                f"{where}: histogram {raw!r} must end in one of "
                f"{'/'.join(_HIST_SUFFIXES)}"
            )
    return problems


# one /metrics sample: name, optional {labels}, value, and (bucket lines
# only) the exemplar suffix `` # {trace_id="<hex>"} <value>``
_EXPOSITION_SAMPLE_RE = re.compile(
    r"""^(?P<name>[a-z_][a-z0-9_]*)
        (?P<labels>\{[^}]*\})?
        [ ](?P<value>[0-9.eE+-]+|\+Inf|-Inf|NaN)
        (?P<exemplar>[ ]\#[ ]\{trace_id="[0-9a-f]+"\}[ ][0-9.eE+-]+)?
        $""",
    re.VERBOSE,
)


def lint_exposition(text: str) -> List[str]:
    """Validate rendered /metrics text (``Metrics.prometheus_text()``):
    every non-comment line must parse as a sample, and an exemplar
    suffix may ride only on histogram ``_bucket`` lines."""
    problems: List[str] = []
    for i, line in enumerate(text.splitlines(), 1):
        if not line or line.startswith("#"):
            continue
        m = _EXPOSITION_SAMPLE_RE.match(line)
        if m is None:
            problems.append(f"exposition line {i}: unparseable sample {line!r}")
            continue
        if m.group("exemplar") and not m.group("name").endswith("_bucket"):
            problems.append(
                f"exposition line {i}: exemplar on non-bucket series "
                f"{m.group('name')!r}"
            )
    return problems


def lint_paths(paths: List[str]) -> List[str]:
    problems: List[str] = []
    for root in paths:
        if os.path.isfile(root):
            files: List[Tuple[str, str]] = [(root, open(root).read())]
        else:
            files = []
            for dirpath, _dirs, names in os.walk(root):
                for n in sorted(names):
                    if n.endswith(".py"):
                        p = os.path.join(dirpath, n)
                        files.append((p, open(p).read()))
        for path, src in files:
            if os.path.basename(path) == os.path.basename(__file__):
                continue  # the linter's own docstring examples
            problems.extend(lint_source(path, src))
    return problems


def default_paths() -> List[str]:
    """The lint scope tier-1 enforces (tests/test_metric_names.py uses
    the same list): every package source plus the repo-root scripts
    that register metrics — the image data plane's labeled decode
    series (``tfk8s_images_decoded_total{mode, backend}``,
    ``tfk8s_image_decode_queue_depth{mode}``, ...) lint through the
    ``tfk8s_tpu`` scan; labels are series identity, so only the NAMES
    are in scope here."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [
        os.path.join(here, "tfk8s_tpu"),
        os.path.join(here, "tools"),
        os.path.join(here, "bench.py"),
    ]


def main(argv: List[str]) -> int:
    paths = argv or default_paths()
    problems = lint_paths(paths)
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} metric-name problem(s)")
        return 1
    print("metric names ok")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
