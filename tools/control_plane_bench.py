"""Control-plane benchmark — the half of the system the reference
actually documents (VERDICT r4 weak #6 / next #5).

The reference's stated hot loop is the worker dequeue loop — "the loop
the whole system's latency hangs off" (SURVEY.md §3.2,
k8s-operator.md:175-180). This harness measures it hermetically (pure
CPU, no tunnel, no TPU): N TPUJobs with their pods churning against the
real store + informer + workqueue + controller machinery, plus the raw
substrate rates underneath. Emitted as the ``control_plane`` block of
bench.py's JSON line and recorded in BASELINE.md.

Sections:

- **store**: raw CRUD rates — creates/s, status-PATCH/s, zero-copy
  selective list/s, and the same with the WAL journal on (fsync off:
  page-cache durability, the kill -9 contract; fsync cost is
  device-dependent and measured separately when it matters);
- **watch fanout**: one writer updating an object stream against W
  concurrent watchers — delivered events/s total (copy-on-write: all
  watchers share one frozen event object) — plus a **slow-watcher arm**:
  one stalled consumer on a small bounded queue must coalesce (latest
  state wins) without slowing the fast watchers;
- **reconcile**: submit N gang jobs against the full informer →
  workqueue → controller loop with an instant-Running node agent;
  jobs/s to the Running condition, per-job submit→Running latency
  p50/p99, peak workqueue depth, and status patches skipped by the
  deep-compare (`status_patches_skipped`);
- **instrumentation**: the same steady-state sync hot path timed twice —
  real Metrics + enabled Tracer vs no-op metrics + disabled tracer —
  reporting the observability tax as a percentage (budget: < 5%).
"""

from __future__ import annotations

import os
import statistics
import tempfile
import threading
import time
from typing import Dict, List

import numpy as np


def _make_job(name: str):
    from tfk8s_tpu.api.types import (
        ContainerSpec, ObjectMeta, ReplicaSpec, ReplicaType, RunPolicy,
        SchedulingPolicy, TPUJob, TPUJobSpec, TPUSpec,
    )

    return TPUJob(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=TPUJobSpec(
            replica_specs={
                ReplicaType.WORKER: ReplicaSpec(
                    replicas=2,
                    template=ContainerSpec(entrypoint="bench:noop"),
                )
            },
            tpu=TPUSpec(accelerator="cpu-1"),
            run_policy=RunPolicy(scheduling=SchedulingPolicy(gang=True)),
        ),
    )


def bench_store(n_writes: int) -> Dict[str, float]:
    from tfk8s_tpu.api import serde
    from tfk8s_tpu.client.store import ClusterStore

    out: Dict[str, float] = {}

    def one(store, tag):
        t0 = time.perf_counter()
        for i in range(n_writes):
            store.create(_make_job(f"{tag}-{i:05d}"))
        out[f"{tag}_creates_per_s"] = round(n_writes / (time.perf_counter() - t0), 1)
        status = serde.to_wire(_make_job("x"))["status"]
        t0 = time.perf_counter()
        for i in range(n_writes):
            store.patch(
                "TPUJob", "default", f"{tag}-{i:05d}",
                {"status": status}, subresource="status",
            )
        out[f"{tag}_status_patches_per_s"] = round(
            n_writes / (time.perf_counter() - t0), 1
        )

    mem = ClusterStore()
    one(mem, "memory")
    # zero-copy selective list: filter runs on the stored objects, only
    # matches are returned (by reference) — the satellite that replaced
    # deepcopy-everything-then-discard
    n_lists = max(n_writes // 10, 20)
    t0 = time.perf_counter()
    for _ in range(n_lists):
        mem.list("TPUJob", "default", {"no-such-label": "x"})
    out["memory_selective_lists_per_s"] = round(
        n_lists / (time.perf_counter() - t0), 1
    )
    with tempfile.TemporaryDirectory(prefix="cpbench-journal-") as d:
        one(ClusterStore(journal_dir=d, fsync=False), "journal")
    # the durability tax, quantified: fsync-per-write is the power-loss-
    # safe default (--no-fsync opts out); a smaller write count keeps the
    # row cheap on slow disks
    with tempfile.TemporaryDirectory(prefix="cpbench-fsync-") as d:
        store = ClusterStore(journal_dir=d, fsync=True)
        n_f = max(n_writes // 10, 20)
        t0 = time.perf_counter()
        for i in range(n_f):
            store.create(_make_job(f"fsync-{i:05d}"))
        out["journal_fsync_creates_per_s"] = round(
            n_f / (time.perf_counter() - t0), 1
        )
    return out


def bench_watch_fanout(watchers: int, updates: int) -> Dict[str, float]:
    from tfk8s_tpu.api.frozen import thaw
    from tfk8s_tpu.client.store import ClusterStore

    store = ClusterStore()
    store.create(_make_job("fan"))
    counts = [0] * watchers
    done = threading.Event()
    ws = [store.watch("TPUJob") for _ in range(watchers)]

    def drain(i, w):
        while counts[i] < updates:
            if w.next(timeout=5.0) is None:
                break
            counts[i] += 1
        if all(c >= updates for c in counts):
            done.set()

    threads = [
        threading.Thread(target=drain, args=(i, w), daemon=True)
        for i, w in enumerate(ws)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    # store reads are shared frozen instances now — thaw for the
    # read-modify-write loop (update_status returns a private copy)
    cur = thaw(store.get("TPUJob", "default", "fan"))
    for _ in range(updates):
        cur.status.gang_restarts += 1
        cur = store.update_status(cur)
    done.wait(timeout=60)
    dt = time.perf_counter() - t0
    for w in ws:
        store.stop_watch(w)
    delivered = sum(counts)
    return {
        "watchers": watchers,
        "updates": updates,
        "delivered_events_per_s": round(delivered / dt, 1),
        "complete": all(c >= updates for c in counts),
    }


def bench_watch_fanout_slow(watchers: int, updates: int) -> Dict[str, float]:
    """The slow-watcher arm: W fast watchers plus ONE stalled consumer on
    a small bounded queue. The coalescing policy must (a) keep the fast
    watchers' delivery complete and fast, (b) bound the slow watcher's
    backlog by merging same-object events (latest state wins), and (c)
    still leave the slow consumer converged on the final state."""
    from tfk8s_tpu.api.frozen import thaw
    from tfk8s_tpu.client.store import ClusterStore

    slow_limit = 16
    store = ClusterStore()
    store.create(_make_job("fan"))
    counts = [0] * watchers
    fast_done = threading.Event()
    ws = [store.watch("TPUJob") for _ in range(watchers)]
    slow_w = store.watch("TPUJob", queue_limit=slow_limit)
    slow = {"delivered": 0, "last_rv": 0}
    slow_done = threading.Event()

    def drain(i, w):
        while counts[i] < updates:
            if w.next(timeout=5.0) is None:
                break
            counts[i] += 1
        if all(c >= updates for c in counts):
            fast_done.set()

    def drain_slow():
        # a consumer ~100x slower than the writer: without coalescing it
        # would backlog `updates` events; with it, backlog <= slow_limit
        while not slow_done.is_set():
            ev = slow_w.next(timeout=0.5)
            if ev is None:
                if fast_done.is_set():
                    break
                continue
            slow["delivered"] += 1
            slow["last_rv"] = ev.object.metadata.resource_version
            time.sleep(0.002)

    threads = [
        threading.Thread(target=drain, args=(i, w), daemon=True)
        for i, w in enumerate(ws)
    ] + [threading.Thread(target=drain_slow, daemon=True)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    cur = thaw(store.get("TPUJob", "default", "fan"))
    for _ in range(updates):
        cur.status.gang_restarts += 1
        cur = store.update_status(cur)
    final_rv = cur.metadata.resource_version
    fast_done.wait(timeout=60)
    dt = time.perf_counter() - t0
    # let the slow consumer finish its (bounded) backlog, then stop it
    deadline = time.time() + 10
    while time.time() < deadline and slow["last_rv"] < final_rv:
        time.sleep(0.01)
    slow_done.set()
    for w in ws:
        store.stop_watch(w)
    store.stop_watch(slow_w)
    return {
        "watchers": watchers,
        "updates": updates,
        "slow_queue_limit": slow_limit,
        "fast_delivered_events_per_s": round(sum(counts) / dt, 1),
        "fast_complete": all(c >= updates for c in counts),
        "slow_delivered": slow["delivered"],
        "slow_coalesced": slow_w.coalesced_total,
        "slow_converged": slow["last_rv"] >= final_rv,
    }


class _InstantKubelet:
    """Marks every PENDING pod Running immediately — isolates the
    control-plane path (informer → queue → reconcile → status write)
    from any data-plane work."""

    def __init__(self, cs):
        self.cs = cs
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _run(self):
        from tfk8s_tpu.api.types import PodPhase
        from tfk8s_tpu.client.store import Conflict, NotFound

        w = self.cs.pods("default").watch()
        while not self._stop.is_set():
            ev = w.next(timeout=0.5)
            if ev is None:
                continue
            pod = ev.object
            if pod.status.phase != PodPhase.PENDING:
                continue
            try:
                cur = self.cs.pods("default").get(pod.metadata.name)
                if cur.status.phase != PodPhase.PENDING:
                    continue
                cur.status.phase = PodPhase.RUNNING
                cur.status.host = "bench-node"
                self.cs.pods("default").update_status(cur)
            except (Conflict, NotFound):
                continue


def bench_reconcile(n_jobs: int) -> Dict[str, float]:
    from tfk8s_tpu.api import helpers
    from tfk8s_tpu.api.types import JobConditionType
    from tfk8s_tpu.client.fake import FakeClientset
    from tfk8s_tpu.trainer.gang import SliceAllocator
    from tfk8s_tpu.trainer.tpujob_controller import TPUJobController

    cs = FakeClientset()
    ctrl = TPUJobController(cs, allocator=SliceAllocator(None))
    kubelet = _InstantKubelet(cs)
    stop = threading.Event()
    depth_samples: List[int] = []
    depth_stop = threading.Event()

    def sample_depth():
        q = ctrl.controller.queue
        while not depth_stop.is_set():
            depth_samples.append(len(q))
            time.sleep(0.002)

    kubelet.start()
    assert ctrl.run(stop=stop, block=False)  # DEFAULT_SYNC_WORKERS
    sampler = threading.Thread(target=sample_depth, daemon=True)
    sampler.start()
    submit_t: Dict[str, float] = {}
    running_t: Dict[str, float] = {}
    try:
        jobs_w = cs.store.watch("TPUJob")
        t0 = time.perf_counter()
        for i in range(n_jobs):
            name = f"cp-{i:04d}"
            cs.tpujobs("default").create(_make_job(name))
            submit_t[name] = time.perf_counter()
        deadline = time.time() + max(60, n_jobs)
        while len(running_t) < n_jobs and time.time() < deadline:
            ev = jobs_w.next(timeout=5.0)
            if ev is None:
                continue
            job = ev.object
            name = job.metadata.name
            if name not in running_t and helpers.has_condition(
                job.status, JobConditionType.RUNNING
            ):
                running_t[name] = time.perf_counter()
        dt = time.perf_counter() - t0
        cs.store.stop_watch(jobs_w)
    finally:
        depth_stop.set()
        kubelet.stop()
        stop.set()
        ctrl.controller.shutdown()
    lats = sorted(
        running_t[n] - submit_t[n] for n in running_t if n in submit_t
    )
    if not lats:
        return {"jobs": n_jobs, "complete": False}
    from tfk8s_tpu.controller.controller import DEFAULT_SYNC_WORKERS

    skipped = ctrl.metrics.get_counter("tfk8s_status_patches_skipped_total")
    return {
        "jobs": n_jobs,
        "workers": DEFAULT_SYNC_WORKERS,
        "complete": len(lats) == n_jobs,
        "jobs_per_s_to_running": round(len(lats) / dt, 1),
        "submit_to_running_p50_ms": round(
            statistics.median(lats) * 1000, 1
        ),
        "submit_to_running_p99_ms": round(
            float(np.quantile(lats, 0.99)) * 1000, 1
        ),
        "workqueue_depth_max": max(depth_samples) if depth_samples else 0,
        "workqueue_depth_mean": round(
            statistics.mean(depth_samples), 2
        ) if depth_samples else 0.0,
        "status_patches_skipped": int(skipped or 0),
    }


class _NullMetrics:
    """Registry with the Metrics surface and no storage — the
    'instrumentation off' arm of the overhead measurement."""

    def describe(self, *a, **kw):
        pass

    def inc(self, *a, **kw):
        pass

    def set_gauge(self, *a, **kw):
        pass

    def observe(self, *a, **kw):
        pass

    def get_gauge(self, *a, **kw):
        return None

    def get_counter(self, *a, **kw):
        return None

    def remove_labels(self, *a, **kw):
        return 0

    def snapshot(self):
        return {"counters": {}, "gauges": {}, "histograms": {}}

    def prometheus_text(self):
        return "\n"


def bench_sync_overhead(n_syncs: int, repeats: int = 4) -> Dict[str, float]:
    """Steady-state reconcile of one Running job, timed with and without
    instrumentation (labeled metrics + spans). Both arms are set up
    FIRST and their measurement rounds interleave — machine drift over
    the bench's lifetime lands on both arms instead of masquerading as
    instrumentation cost; min-of-rounds is the stablest statistic."""
    from tfk8s_tpu.api import helpers
    from tfk8s_tpu.api.types import JobConditionType
    from tfk8s_tpu.client.fake import FakeClientset
    from tfk8s_tpu.obs.trace import Tracer
    from tfk8s_tpu.trainer import tpujob_controller as tc
    from tfk8s_tpu.trainer.gang import SliceAllocator
    from tfk8s_tpu.trainer.tpujob_controller import TPUJobController
    from tfk8s_tpu.utils.logging import Metrics

    stop = threading.Event()
    arms: Dict[str, Dict] = {}
    # Suspend the periodic node-liveness re-enqueue for the measurement:
    # each timed sync schedules a +NODE_CHECK_PERIOD_S re-sync of the
    # same key, so hammering one key n_syncs times in a few seconds
    # builds a delayed backlog whose background drain lands inside the
    # OTHER arm's next timed round (the arms interleave) — measured as
    # up to ~30% phantom "overhead". The arm measures sync cost, not
    # the recheck scheduler; park the recheck out past the bench.
    saved_period = tc.NODE_CHECK_PERIOD_S
    tc.NODE_CHECK_PERIOD_S = 3600.0
    try:
        for label, instrumented in (("bare", False), ("instrumented", True)):
            cs = FakeClientset()
            ctrl = TPUJobController(
                cs,
                allocator=SliceAllocator(None),
                metrics=Metrics() if instrumented else _NullMetrics(),
                tracer=Tracer(enabled=instrumented),
            )
            kubelet = _InstantKubelet(cs)
            kubelet.start()
            assert ctrl.run(workers=1, stop=stop, block=False)
            cs.tpujobs("default").create(_make_job("ovh"))
            deadline = time.time() + 30
            while time.time() < deadline:
                j = cs.tpujobs("default").get("ovh")
                if helpers.has_condition(j.status, JobConditionType.RUNNING):
                    break
                time.sleep(0.01)
            for _ in range(20):  # warm caches / allocator paths
                ctrl.sync("default/ovh")
            arms[label] = {
                "ctrl": ctrl, "kubelet": kubelet, "best": float("inf"),
            }
        for _ in range(repeats):
            for arm in arms.values():
                t0 = time.perf_counter()
                for _ in range(n_syncs):
                    arm["ctrl"].sync("default/ovh")
                arm["best"] = min(
                    arm["best"], (time.perf_counter() - t0) / n_syncs
                )
    finally:
        tc.NODE_CHECK_PERIOD_S = saved_period
        stop.set()
        for arm in arms.values():
            arm["kubelet"].stop()
            arm["ctrl"].controller.shutdown()
    bare, inst = arms["bare"]["best"], arms["instrumented"]["best"]
    return {
        "syncs": n_syncs,
        "sync_us_bare": round(bare * 1e6, 2),
        "sync_us_instrumented": round(inst * 1e6, 2),
        "overhead_pct": round((inst - bare) / bare * 100.0, 2),
    }


def run_all(small: bool = False) -> Dict[str, object]:
    n_writes = 200 if small else 2000
    watchers = 4 if small else 16
    updates = 100 if small else 1000
    n_jobs = 8 if small else 64
    n_syncs = 300 if small else 1500
    return {
        "small": small,
        **bench_store(n_writes),
        "watch_fanout": bench_watch_fanout(watchers, updates),
        "watch_fanout_slow": bench_watch_fanout_slow(watchers, updates),
        "reconcile": bench_reconcile(n_jobs),
        "instrumentation": bench_sync_overhead(n_syncs),
    }


def main() -> None:
    import json

    small = os.environ.get("BENCH_SMALL") == "1"
    print(json.dumps({"control_plane": run_all(small=small)}))


if __name__ == "__main__":
    main()
