"""Headline benchmark: ResNet-50 images/sec/chip (BASELINE.json "metric"),
plus BERT-base MLM step-time — the second BASELINE.md target metric.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} with the
secondary BERT measurement under "extra".

The reference publishes no numbers (`BASELINE.json "published": {}`,
SURVEY.md §6), so ``vs_baseline`` compares against the last recorded run
of *this* repo (BENCH_BASELINE.json, committed after each round) — 1.0 on
the first measurement.

Runs on whatever backend JAX finds: the driver runs it on the one real
TPU chip; set BENCH_SMALL=1 for a seconds-scale CPU smoke run.

All timed steps run inside ONE jitted ``lax.scan`` — a single dispatch
with a strict device-side dependency chain, immune to async-dispatch
timing artifacts. Pre-staged batches are passed as a jit ARGUMENT (never
captured in the closure: closed-over device arrays are baked into the HLO
as constants, which bloats the program by hundreds of MB and broke the
round-1 remote compile with HTTP 413).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


_WINDOWS = 3


def _median_window(timed_once, windows: int = _WINDOWS):
    """(median, all_window_seconds) of ``windows`` calls to ``timed_once``
    (a no-arg callable that runs AND host-syncs one timed region).
    Single windows swing ~±15% on this device (thermal / tunnel
    contention); the median is repeatable to ±0.3%. The raw windows ride
    the output's ``noise`` block so every BENCH_r*.json self-describes
    its spread (VERDICT r3 next #9)."""
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        timed_once()
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2], times


def _time_task(task, mesh, steps: int, n_stage: int = 4):
    """(seconds-per-step, per-window seconds-per-step list), measured over
    ``steps`` scanned steps."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tfk8s_tpu.runtime.train import TrainConfig, Trainer

    trainer = Trainer(task, TrainConfig(steps=steps, learning_rate=1e-3), mesh)
    state = trainer.init_state()
    shardings = trainer.batch_shardings
    rng = np.random.default_rng(0)

    host = [task.make_batch(rng, task.batch_size) for _ in range(n_stage)]
    stacked = jax.device_put(
        jax.tree_util.tree_map(lambda *xs: np.stack(xs), *host),
        jax.tree_util.tree_map(
            lambda s: NamedSharding(s.mesh, P(None, *s.spec)), shardings
        ),
    )

    def run_n(state, staged, n):
        def body(s, i):
            batch = jax.tree_util.tree_map(lambda x: x[i % n_stage], staged)
            s, metrics = trainer._step_fn(
                s, batch, jax.random.fold_in(jax.random.key(0), i)
            )
            return s, metrics["loss"]

        return jax.lax.scan(body, state, jnp.arange(n))

    run = jax.jit(run_n, static_argnums=2)
    # Warm with the SAME static n as the timed call — a different scan
    # length is a different HLO, and the recompile would land inside the
    # timed region. Fetch a loss to the host to force completion: through
    # the remote-execution tunnel block_until_ready can return before the
    # device work drains, so a host transfer is the only honest barrier.
    state, losses = run(state, stacked, steps)  # compile + warm
    float(np.asarray(losses)[-1])

    def timed_once():
        _state, losses = run(state, stacked, steps)
        float(np.asarray(losses)[-1])

    med, windows = _median_window(timed_once)
    return med / steps, [w / steps for w in windows]


def _fit_step_time(task, mesh, steps: int, scan_steps: int = 1):
    """(median seconds-per-step, per-window list) through the PRODUCT
    loop — ``Trainer.fit`` with
    its background prefetch pipeline, per-step ``device_put`` and all —
    so the published scanned number and what ``fit`` delivers can be
    compared (VERDICT r2 next #3). ``scan_steps`` > 1 measures the
    production host-loop chunking (TFK8S_SCAN_STEPS) that amortizes the
    per-dispatch tunnel overhead — same trajectory, k steps per dispatch.
    Compile happens on a primed step before the clock starts."""
    import jax
    import numpy as np

    from tfk8s_tpu.runtime.train import TrainConfig, Trainer

    trainer = Trainer(
        task,
        TrainConfig(steps=steps + 1, learning_rate=1e-3, log_every=steps + 1,
                    # prefetch must cover the chunk: a k-step dispatch
                    # needs k host batches READY — a depth-2 queue would
                    # leave the device idle while the producer
                    # synthesizes the other k-2
                    prefetch=max(2, scan_steps + 2), scan_steps=scan_steps),
        mesh,
    )
    host = trainer.prepare_batch(
        task.make_batch(np.random.default_rng(0), task.batch_size)
    )
    if scan_steps > 1:
        # the chunked loop dispatches through _chunk_fn(k) — prime THAT
        # compile with a throwaway state (the chunk donates its state
        # argument, so the warm state is consumed)
        if (steps + 1) % scan_steps:
            raise ValueError("steps+1 must divide by scan_steps (one chunk "
                             "shape -> one compile, kept out of the clock)")
        warm_state = trainer.init_state()
        stacked = jax.device_put(
            jax.tree_util.tree_map(
                lambda x: np.stack([np.asarray(x)] * scan_steps), host
            ),
            trainer.stacked_batch_shardings,
        )
        _st, ys = trainer._chunk_fn(scan_steps)(
            warm_state, stacked, jax.random.key(0)
        )
        float(np.asarray(ys["loss"])[-1])  # honest host barrier
        state = trainer.init_state()
    else:
        state = trainer.init_state()
        batch = jax.device_put(host, trainer.batch_shardings)
        state, metrics = trainer._step_fn(state, batch, jax.random.key(0))
        float(metrics["loss"])  # compile + warm with an honest host barrier

    # median of 3 full fit passes (fresh state each, compile shared via
    # the same Trainer): a single window is exposed to transient tunnel
    # stalls — one observed run measured 315 ms/step (7.7x) on a row
    # whose neighbors timed 43 ms before and after
    per_step = []
    for w in range(_WINDOWS):
        wstate = state if w == 0 else trainer.init_state()
        start_step = int(wstate.step)
        t0 = time.perf_counter()
        wstate, _history = trainer.fit(state=wstate)
        # fit's final log line already fetched metrics to the host
        dt = time.perf_counter() - t0
        done = int(wstate.step) - start_step
        per_step.append(dt / max(done, 1))
    return sorted(per_step)[len(per_step) // 2], per_step


def _flash_speedup(seq: int = 2048, iters: int = 8, blocks=None,
                   masked: bool = False, b: int = 8, h: int = 12,
                   d: int = 64):
    """Train-shaped attention (fwd+bwd, bf16) at BERT-base head geometry:
    Pallas flash kernels vs the XLA einsum path. ``masked=False`` is the
    causal pretraining shape; ``masked=True`` exercises the key-padding
    path the kernels ship for BERT/T5 batches (non-causal, variable
    valid lengths per row — the mask-capable path VERDICT r3 noted the
    bench never measured). Returns (flash_ms, xla_ms) per fwd+bwd."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfk8s_tpu.models.transformer import dot_product_attention
    from tfk8s_tpu.ops.flash_attention import flash_attention

    if blocks is not None:
        flash_attention = functools.partial(
            flash_attention, block_q=blocks[0], block_k=blocks[1]
        )

    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(rng.standard_normal((b, seq, h, d)), jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    causal = not masked
    mask = None
    if masked:
        # realistic padding: per-row valid lengths in [seq/2, seq]
        valid = rng.integers(seq // 2, seq + 1, size=(b,))
        mask = jnp.asarray(np.arange(seq)[None, :] < valid[:, None])

    def time_one(attn) -> float:
        def loss(q, k, v):
            out = (
                attn(q, k, v, mask=mask, causal=causal)
                if masked
                else attn(q, k, v, causal=causal)
            )
            return jnp.sum(out.astype(jnp.float32) ** 2)

        grad = jax.grad(loss, argnums=(0, 1, 2))
        # ALL three grads feed the scan carry (body_kv below) — leaving
        # dk/dv out of the dependency chain would let XLA dead-code-
        # eliminate the dkv half of the backward and undercount the work.
        # DIFFERENCED timing (same method as tools/roofline.py): one
        # window through the tunnel costs a fixed ~50-110 ms round trip
        # on top of the device work, so a single-length window reports
        # fixed + work and UNDERSTATES any speedup — at seq 8192 / b1h4
        # the r4 artifact recorded 1.16x where the marginal-cost truth is
        # ~4x. Timing two scan lengths and differencing cancels the fixed
        # cost exactly; median-of-3 windows each side keeps the noise
        # floor below the 4*iters marginal iterations being measured.
        def window_of(n):
            # k/v ride as jit ARGUMENTS — closing over the device arrays
            # would bake ~48 MB of constants into each HLO, and the
            # differenced method doubles the compile count (the round-1
            # remote-compile 413 failure mode the autotune comment
            # documents)
            def _scan(q, k, v):
                def body_kv(c, _):
                    dq, dk, dv = grad(c, k, v)
                    return c + 0.0 * (dq + dk + dv).astype(c.dtype), ()

                return jax.lax.scan(body_kv, q, None, length=n)[0]

            run = jax.jit(_scan)
            out = run(q, k, v)
            float(np.asarray(out[0, 0, 0, 0]))  # compile + warm (host barrier)

            def timed_once():
                out = run(q, k, v)
                float(np.asarray(out[0, 0, 0, 0]))

            return _median_window(timed_once)[0]

        t1 = window_of(iters)
        t2 = window_of(5 * iters)
        return (t2 - t1) / (4 * iters) * 1000

    return time_one(flash_attention), time_one(dot_product_attention)


def _tunnel_probes(task, mesh):
    """MEASURED per-step tunnel costs, so the fit-vs-scanned gap is
    bounded in the artifact instead of asserted in prose (VERDICT r3
    next #7). Three numbers:

    - sync round trip: dispatch + 4-byte fetch (what ANY per-scalar
      ``float()`` costs mid-loop — ~50-100 ms on the remote rig, which
      is why fit batches its metric fetches and drains its inflight
      window with one fetch per half-window);
    - dispatch enqueue: the async per-call host cost the fit loop
      actually pays per step (~0.1 ms — dispatches pipeline);
    - h2d per batch: staging one host batch (enqueue + transfer drain).

    Returns (sync_rtt_s, enqueue_s, h2d_s_per_batch, batch_bytes)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfk8s_tpu.runtime.train import TrainConfig, Trainer

    trainer = Trainer(task, TrainConfig(steps=1), mesh)
    host_batch = task.make_batch(np.random.default_rng(1), task.batch_size)
    shardings = trainer.batch_shardings

    inc = jax.jit(lambda x: x + 1.0)
    x = jnp.float32(0)
    float(inc(x))  # compile

    def rtt_once():
        float(inc(x))  # dispatch + 4-byte fetch: one full round trip

    rtt, _ = _median_window(rtt_once, windows=9)

    n_enq = 64
    y = jnp.float32(0)
    t0 = time.perf_counter()
    for _ in range(n_enq):
        y = inc(y)
    enqueue = (time.perf_counter() - t0) / n_enq
    float(y)  # drain the chain

    batch_bytes = int(
        sum(np.asarray(l).nbytes for l in jax.tree_util.tree_leaves(host_batch))
    )

    def h2d_once():
        dev = jax.device_put(host_batch, shardings)
        leaf = jax.tree_util.tree_leaves(dev)[0]
        # reduce ON DEVICE, fetch the scalar — the honest completion
        # barrier without pulling the batch back through the tunnel
        float(jnp.sum(leaf.astype(jnp.float32)))

    h2d_once()  # warm the reduce's compile
    h2d_total, _ = _median_window(h2d_once, windows=5)
    return rtt, enqueue, max(h2d_total - rtt, 0.0), batch_bytes


def _gpt_decode_ms_per_token(small: bool, batch: Optional[int] = None):
    """Autoregressive serving shape: greedy KV-cache decoding — batched
    prefill + one jitted decode scan, the whole generation a single
    dispatch through the tunnel. Params served in bfloat16 (the serving
    standard; halves per-step param HBM traffic — measured 1.14x at
    batch 8, the rest of the step is cache/launch-bound). Returns
    (ms_per_generated_token, generated_tokens_per_sec,
    per_window_ms_list) at GPT-2-small shape, random params — decode
    cost is shape-, not value-, dependent. ``batch`` overrides the
    default batch 8 (throughput scales with batching: 15.6k vs 6.8k
    generated tok/s at batch 32 vs 8, both bf16 — 2.3x)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfk8s_tpu.models import gpt
    from tfk8s_tpu.parallel.sharding import unbox

    if small:
        cfg = gpt.tiny_config(max_len=48)
        batch, prompt_len, num_tokens = batch or 2, 16, 16
    else:
        cfg = gpt.base_config(max_len=1024)
        batch, prompt_len, num_tokens = batch or 8, 128, 128
    task = gpt.make_task(cfg=cfg, seq_len=prompt_len, batch_size=batch)
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16),
        unbox(task.init(jax.random.key(0))),
    )
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(1, cfg.vocab_size, (batch, prompt_len)),
        jnp.int32,
    )

    run = jax.jit(
        lambda p, pr: gpt.greedy_generate(cfg, p, pr, num_tokens=num_tokens)
    )
    out = run(params, prompt)
    np.asarray(out)  # compile + warm, honest host barrier

    def timed_once():
        np.asarray(run(params, prompt))

    # the serving rows were the noisiest in r4 (34% window spread where
    # the fit rows hold ±2% — VERDICT r4 weak #4): each window is ONE
    # ~1s generation, so a single tunnel stall dominates it. Two fixes:
    # more windows (7 vs 3), and one SETTLE generation after the compile
    # warmup — the first post-warmup window measures reproducibly ~25%
    # faster than steady state (r4: 0.977 vs ~1.30; r5: 0.938 vs ~1.26;
    # dispatch pipelining against the still-warm device queue), so it
    # belongs to warmup, not to the serving rate being reported.
    timed_once()  # settle: absorb the fast first window
    n_win = int(os.environ.get("BENCH_DECODE_WINDOWS", "3" if small else "7"))
    sec, windows = _median_window(timed_once, windows=n_win)
    # generation runs ONE batched-prefill dispatch (prompt-parallel
    # matmuls) + num_tokens decode steps; ms_per_token divides the
    # END-TO-END time by GENERATED tokens (prefill cost amortized in),
    # and throughput counts generated tokens only — prompt positions are
    # input, not output
    return (
        sec / num_tokens * 1000,
        batch * num_tokens / sec,
        [w / num_tokens * 1000 for w in windows],
    )


def _recordio_probe(small: bool):
    """Input-pipeline throughput on THIS host: write a shard of
    float32-array examples, then measure (a) the native C++ reader's
    CRC-verified bulk read and (b) the pure-Python fallback on a smaller
    slice (its byte-at-a-time CRC is ~1000x slower — measuring the full
    shard would dominate the bench), plus the full read+decode+stack
    dataset path. Host-side only — no accelerator involvement. Returns a
    dict or None when the native lib is unavailable (the comparison is
    the point)."""
    import shutil
    import tempfile

    import numpy as np

    from tfk8s_tpu.data import RecordDataset, RecordFile, RecordWriter, encode
    from tfk8s_tpu.data import _native

    if _native.load() is None:
        return None
    n_rec, leaf = (64, 4096) if small else (512, 32768)  # ~1 MB / ~64 MB
    rng = np.random.default_rng(0)
    d = tempfile.mkdtemp(prefix="bench-recordio-")
    try:
        path = os.path.join(d, "shard.rio")
        payload = [
            encode({"x": rng.standard_normal(leaf).astype(np.float32)})
            for _ in range(min(n_rec, 32))
        ]
        t0 = time.perf_counter()
        with RecordWriter(path) as w:
            for i in range(n_rec):
                w.write(payload[i % len(payload)])
        write_s = time.perf_counter() - t0
        nbytes = os.path.getsize(path)

        rf = RecordFile(path)
        idx = list(range(len(rf)))

        def read_all():
            rf.read(idx, verify=True)

        read_all()  # page cache warm
        native_s, _ = _median_window(read_all)

        # pure-python fallback on a 1/16 slice, rate scaled from its bytes
        py_slice = idx[: max(len(idx) // 16, 1)]
        py_bytes = sum(rf.lengths[i] for i in py_slice)
        try:
            # deliberate measurement of the fallback, not an outage —
            # pre-latch the once-per-process warning so the bench log
            # doesn't cry wolf about a native reader that IS available
            from tfk8s_tpu.data import recordio as _rio

            _rio._fallback_warned = True
            _native._tried, _native._lib, saved = True, None, _native._lib
            py_rf = RecordFile(path)
            t0 = time.perf_counter()
            py_rf.read(py_slice, verify=True)
            py_s = time.perf_counter() - t0
        finally:
            _native._lib, _native._tried = saved, True

        ds = RecordDataset([path], batch_size=min(32, n_rec), seed=0)
        it = iter(ds.batches(0))
        t0 = time.perf_counter()
        n_batches = sum(1 for _ in it)
        ds_s = time.perf_counter() - t0
        native_rate, py_rate = nbytes / native_s, py_bytes / py_s
        return {
            "recordio_shard_mb": round(nbytes / 1e6, 1),
            "recordio_write_mbps": round(nbytes / write_s / 1e6, 1),
            "recordio_native_read_mbps": round(native_rate / 1e6, 1),
            "recordio_python_read_mbps": round(py_rate / 1e6, 1),
            "recordio_native_speedup": round(native_rate / py_rate, 1),
            "recordio_pipeline_mbps": round(nbytes / ds_s / 1e6, 1),
            "recordio_pipeline_batches_per_s": round(n_batches / ds_s, 1),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


# images/s the ResNet-50 headline consumes at the measured step rate —
# the decode pool must deliver at least this or input starves the chip
# (ISSUE 2 budget: >=2,447 img/s, ~370 MB/s decoded float32 at 224px)
_IMAGE_BUDGET_IMG_S = 2447


def _image_pipeline_probe(small: bool):
    """Image data-plane throughput on THIS host: pack a synthetic JPEG
    shard set (data/images/pack.py), then run the decode+augment worker
    pool (ImageDataset) over one epoch per row. Rows (all measured in
    the SAME run, workers=1 so they read as per-worker img/s):

    - delivered: the env-resolved backend at the default pool width —
      the rate a training host actually gets, vs the input budget;
    - per-backend: native vs PIL at the headline source size, plus the
      native DCT-scaled-decode on/off pair;
    - hi-res: the same backend pair on multi-megapixel sources (the
      regime scaled decode exists for — a 1024px source bound for a
      224px crop decodes at a fraction of the IDCT cost).

    Host-side only. Returns None when no image decoder is importable;
    the native rows are present only when the native core actually
    loaded (TFK8S_PURE_PY / missing toolchain degrade to the PIL rows)."""
    import shutil
    import tempfile

    from tfk8s_tpu.data.images import ImageDataset, pack
    from tfk8s_tpu.data.images import _native_decode
    from tfk8s_tpu.data.images.decode import have_decoder

    if not have_decoder():
        return None
    # small: tiny images for rc coverage; full: the headline 224px shape
    n, size, classes, bs = (96, 64, 8, 32) if small else (768, 224, 16, 64)
    hi_n, hi_size = (48, 128) if small else (96, 1024)

    def rate(paths, backend, scaled=True, workers=1, train_size=size):
        ds = ImageDataset(
            paths, batch_size=bs, image_size=train_size, train=True,
            seed=0, workers=workers, backend=backend,
            scaled_decode=scaled,
        )
        try:
            next(iter(ds.batches(0)))  # warm: pool spin-up + page cache
            decoded0, bytes0 = ds.images_decoded, ds.decoded_bytes
            t0 = time.perf_counter()
            for _ in ds.batches(0):
                pass
            dt = time.perf_counter() - t0
            imgs = ds.images_decoded - decoded0
            dec_mb = (ds.decoded_bytes - bytes0) / 1e6
            return imgs / dt, dec_mb / dt, ds.workers, ds.backend
        finally:
            ds.close()  # a mid-measure decode error must not leak the pool

    native = _native_decode.available()
    d = tempfile.mkdtemp(prefix="bench-images-")
    try:
        paths = pack.pack_synthetic(d, n, classes, size, 2, seed=0)
        shard_mb = sum(os.path.getsize(p) for p in paths) / 1e6
        # the delivered rate: env-resolved backend, default pool width
        img_s, dec_mbps, pool_w, backend = rate(
            paths, backend=None, workers=None
        )
        pil_s, _, _, _ = rate(paths, backend="pil")
        block = {
            "image_decode_images_per_sec": round(img_s, 1),
            "image_decode_mbps_decoded": round(dec_mbps, 1),
            "image_decode_workers": pool_w,
            "image_backend": backend,
            "image_px": size,
            "image_shard_mb": round(shard_mb, 1),
            "image_budget_images_per_sec": _IMAGE_BUDGET_IMG_S,
            "img_per_sec_pil": round(pil_s, 1),
            # the budget describes the FULL 224px shape; small mode's
            # tiny images would claim a meaningless pass
            **(
                {"image_meets_budget": bool(img_s >= _IMAGE_BUDGET_IMG_S)}
                if not small
                else {}
            ),
        }
        if native:
            nat_s, _, _, _ = rate(paths, backend="native")
            nat_u, _, _, _ = rate(paths, backend="native", scaled=False)
            block.update(
                {
                    "img_per_sec_native": round(nat_s, 1),
                    "img_per_sec_native_unscaled": round(nat_u, 1),
                    "image_native_vs_pil": round(nat_s / pil_s, 2),
                }
            )
        # hi-res sources: where DCT-scaled decode actually bites
        hd = os.path.join(d, "hires")
        hi_paths = pack.pack_synthetic(hd, hi_n, classes, hi_size, 2, seed=1)
        hi_pil, _, _, _ = rate(hi_paths, backend="pil")
        block["image_hires_px"] = hi_size
        block["img_per_sec_pil_hires"] = round(hi_pil, 1)
        if native:
            hi_nat, _, _, _ = rate(hi_paths, backend="native")
            hi_nat_u, _, _, _ = rate(
                hi_paths, backend="native", scaled=False
            )
            block.update(
                {
                    "img_per_sec_native_hires": round(hi_nat, 1),
                    "img_per_sec_native_hires_unscaled": round(hi_nat_u, 1),
                }
            )
        return block
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _serving_probe(small: bool, full: bool = False):
    """Serving-path throughput on THIS host (no control plane, no chip):
    the TPUServe data plane — runtime/server.ModelServer around the jitted
    MLP classifier — driven by an OPEN-LOOP offered-QPS sweep. Per rate:
    achieved QPS, p50/p99 end-to-end latency, mean batch occupancy, and
    the shed count (bounded-queue backpressure). The headline scalars come
    from the highest-throughput row; the full sweep rides the detail
    block. ``full=True`` forces the full-size sweep inside BENCH_SMALL
    (the standalone issue artifact path)."""
    import numpy as np

    from tfk8s_tpu.runtime.server import MlpClassifier, ModelServer, Overloaded
    from tfk8s_tpu.utils.logging import Metrics

    small_mode = small and not full
    if small_mode:
        rates, dur, hidden = (100, 400), 1.0, 32
    else:
        # the top rate is past the measured 1-core ceiling (~13k QPS at
        # occupancy 16) so the sweep always shows saturation: achieved <
        # offered with the p99 blowing out — the documented serving ceiling
        rates, dur, hidden = (250, 1000, 4000, 16000), 3.0, 256
    # queue_limit deliberately BELOW the load generator's in-flight cap
    # (_MAX_INFLIGHT submitter threads): past saturation the bounded
    # queue actually fills and the shed/backpressure path is measured,
    # not just structurally unreachable
    max_batch, timeout_ms, queue_limit = 16, 2.0, 64
    model = MlpClassifier("seed:0", max_batch_size=max_batch, hidden=hidden)
    model.load()
    server = ModelServer(
        model, max_batch_size=max_batch, batch_timeout_s=timeout_ms / 1000.0,
        queue_limit=queue_limit, metrics=Metrics(),
    ).start()
    payload = np.random.default_rng(0).standard_normal(784).astype(np.float32)
    try:
        server.submit(payload, timeout=120)  # compile + warm
        from concurrent.futures import ThreadPoolExecutor

        def one():
            t0 = time.perf_counter()
            try:
                server.submit(payload, timeout=30)
                return time.perf_counter() - t0
            except Overloaded:
                return None

        _MAX_INFLIGHT = 256  # > queue_limit, so overload reaches the queue bound
        sweep = []
        for rate in rates:
            n = int(rate * dur)
            interval = 1.0 / rate
            served0, batches0 = server.served_total, server.batches_total
            futs = []
            with ThreadPoolExecutor(max_workers=_MAX_INFLIGHT) as pool:
                t_start = time.perf_counter()
                for i in range(n):
                    # open-loop arrivals: the clock, not the responses,
                    # paces submission — saturation shows as achieved <
                    # offered plus shed, the honest serving measurement
                    target = t_start + i * interval
                    now = time.perf_counter()
                    if target > now:
                        time.sleep(target - now)
                    futs.append(pool.submit(one))
                results = [f.result() for f in futs]
                elapsed = time.perf_counter() - t_start
            lat = sorted(r for r in results if r is not None)
            shed = len(results) - len(lat)
            occ = (server.served_total - served0) / max(
                server.batches_total - batches0, 1
            )
            sweep.append({
                "offered_qps": rate,
                "achieved_qps": round(len(lat) / elapsed, 1),
                "p50_ms": round(lat[len(lat) // 2] * 1000, 3) if lat else None,
                "p99_ms": round(
                    lat[min(int(len(lat) * 0.99), len(lat) - 1)] * 1000, 3
                ) if lat else None,
                "mean_batch_occupancy": round(occ, 2),
                "shed": shed,
            })
    finally:
        server.drain(timeout=10)
    best = max(sweep, key=lambda r: r["achieved_qps"])
    return {
        "serving_model": f"mlp-{hidden}",
        "serving_max_batch": max_batch,
        "serving_batch_timeout_ms": timeout_ms,
        "serving_queue_limit": queue_limit,
        "serving_sweep": sweep,
        "serving_qps": best["achieved_qps"],
        "serving_p50_ms": best["p50_ms"],
        "serving_p99_ms": best["p99_ms"],
        "serving_batch_occupancy": best["mean_batch_occupancy"],
        "serving_shed_total": sum(r["shed"] for r in sweep),
    }


def _gateway_probe(small: bool, full: bool = False):
    """Gateway front-door throughput (ISSUE 10): the serving sweep's
    open-loop offered-QPS ladder driven THROUGH THE WIRE — a real
    GatewayServer on a real socket, real keep-alive GatewayClients,
    least-loaded routing over replicas the actual controller + kubelet
    brought up — against an in-process ServeClient baseline on the SAME
    replica set at the same rates (acceptance: wire >= 70% of in-process
    at the top offered rate). Then a fairness round: well-behaved
    tenants' served QPS measured alone and again with one tenant
    offering 10x its quota — ``gateway_fairness_ratio`` is with/without
    (acceptance: the abuser costs the innocent < 10%). Every shed must
    arrive typed; ``gateway_shed_untyped`` counts wire errors outside
    the taxonomy and must be 0."""
    import threading
    from concurrent.futures import ThreadPoolExecutor

    import tfk8s_tpu.runtime.kubelet as kubelet_mod
    import tfk8s_tpu.trainer.serve_controller as sc_mod
    from tfk8s_tpu.api.types import (
        BatchingPolicy,
        ObjectMeta,
        TPUServe,
        TPUServeSpec,
    )
    from tfk8s_tpu.client import FakeClientset
    from tfk8s_tpu.client.store import StoreError
    from tfk8s_tpu.gateway.client import GatewayClient
    from tfk8s_tpu.gateway.server import GatewayServer
    from tfk8s_tpu.obs import trace as obstrace
    from tfk8s_tpu.runtime import LocalKubelet
    from tfk8s_tpu.runtime.server import ServeClient, ServeError
    from tfk8s_tpu.trainer import TPUServeController
    from tfk8s_tpu.utils.logging import Metrics

    small_mode = small and not full
    if small_mode:
        rates, dur = (100, 400), 1.0
        fair_dur, good_rate, abuse_quota = 1.0, 50, 10.0
    else:
        rates, dur = (250, 1000, 4000), 3.0
        fair_dur, good_rate, abuse_quota = 2.0, 100, 20.0
    replicas, delay_ms = 2, 1.0

    flush0 = kubelet_mod.LOG_FLUSH_SECONDS
    period0 = sc_mod.AUTOSCALE_PERIOD_S
    # the untraced baseline must truly be untraced: park the process
    # tracer behind a disabled one for the main sweeps; the traced arm
    # swaps in a live tracer + tail sampler for its re-run only
    prev_tracer = obstrace.set_tracer(obstrace.Tracer(enabled=False))
    kubelet_mod.LOG_FLUSH_SECONDS = 0.05
    sc_mod.AUTOSCALE_PERIOD_S = 0.1
    cs = FakeClientset()
    ctrl = TPUServeController(cs)
    kubelet = LocalKubelet(cs)
    stop = threading.Event()
    kubelet.run(stop)
    ctrl.run(workers=2, stop=stop, block=False)
    gw = GatewayServer(cs, port=0, metrics=Metrics())
    gw.serve_background()
    name = "bench-gw"
    try:
        serve = TPUServe(
            metadata=ObjectMeta(name=name),
            spec=TPUServeSpec(
                task="echo", checkpoint="v1", replicas=replicas,
                batching=BatchingPolicy(
                    max_batch_size=16, batch_timeout_ms=2.0, queue_limit=64
                ),
            ),
        )
        serve.spec.template.env["TFK8S_SERVE_ECHO_DELAY_MS"] = str(delay_ms)
        cs.tpuserves().create(serve)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                if cs.tpuserves().get(name).status.ready_replicas == replicas:
                    break
            except StoreError:
                pass
            time.sleep(0.05)
        else:
            raise RuntimeError("gateway bench replicas never became Ready")

        shed = {"typed": 0, "untyped": 0}
        shed_lock = threading.Lock()

        def one_timed(request_fn):
            t0 = time.perf_counter()
            try:
                request_fn()
                return time.perf_counter() - t0
            except (ServeError, StoreError):
                with shed_lock:
                    shed["typed"] += 1
                return None
            except Exception:  # noqa: BLE001 — an UNtyped wire error
                with shed_lock:
                    shed["untyped"] += 1
                return None

        def sweep_with(request_fn, use_rates=None):
            # same open-loop pacing as _serving_probe: the clock, not the
            # responses, paces submission
            sweep = []
            for rate in (rates if use_rates is None else use_rates):
                n = int(rate * dur)
                interval = 1.0 / rate
                futs = []
                # 64 submitters, not 128: at ~4ms/request 64 covers 4x the
                # top offered rate, and every extra idle thread costs GIL
                # handoffs that the single-process wire path pays twice
                # (client and server threads share the interpreter)
                with ThreadPoolExecutor(max_workers=64) as pool:
                    t_start = time.perf_counter()
                    for i in range(n):
                        target = t_start + i * interval
                        now = time.perf_counter()
                        if target > now:
                            time.sleep(target - now)
                        futs.append(pool.submit(one_timed, request_fn))
                    results = [f.result() for f in futs]
                    elapsed = time.perf_counter() - t_start
                lat = sorted(r for r in results if r is not None)
                sweep.append({
                    "offered_qps": rate,
                    "achieved_qps": round(len(lat) / elapsed, 1),
                    "p50_ms": round(lat[len(lat) // 2] * 1000, 3)
                    if lat else None,
                    "p99_ms": round(
                        lat[min(int(len(lat) * 0.99), len(lat) - 1)] * 1000, 3
                    ) if lat else None,
                    "shed": len(results) - len(lat),
                })
            return sweep

        wire_client = GatewayClient(gw.url, name)
        wire_client.request(1.0, timeout=30)  # warm route table + socket
        wire = sweep_with(lambda: wire_client.request(1.0, timeout=10))
        inproc_client = ServeClient(cs, name)
        inproc = sweep_with(lambda: inproc_client.request(1.0, timeout=10))

        # -- traced re-run (ISSUE 11): the SAME wire workload at the top
        # offered rate with the request-tracing pipeline live — W3C
        # propagation client -> gateway -> replica, tail sampling at the
        # default keep probability, exemplars, ring export. Acceptance:
        # achieved QPS within 5% of the untraced wire run at this rate.
        traced_tracer = obstrace.Tracer()
        traced_tracer.set_sampler(obstrace.TailSampler())
        obstrace.set_tracer(traced_tracer)
        try:
            traced = sweep_with(
                lambda: wire_client.request(1.0, timeout=10),
                use_rates=rates[-1:],
            )[0]
        finally:
            obstrace.set_tracer(obstrace.Tracer(enabled=False))
        # ring-sizing audit: at the top benched rate the default
        # TFK8S_TRACE_RING capacity plus the tail sampler must not evict
        # kept spans — ring_full == 0 says the ring is sized for this
        # load; "sampled" counts the fast successes the sampler shed
        trace_dropped = dict(traced_tracer.dropped)
        trace_kept = len(traced_tracer.spans())

        # -- fairness round: N tenants, then the same N plus one tenant
        # offering 10x ITS quota — its excess must die at its own bucket,
        # not in the queue everyone shares
        cs.tpuserves().patch(name, {"spec": {"tenancy": {
            "enabled": True,
            "defaultQuota": {"qps": 100000.0, "burst": 1024},
            "tenants": {
                "abuser": {"qps": abuse_quota, "burst": int(abuse_quota)},
            },
        }}})
        time.sleep(1.2)  # past the gateway's spec TTL: policy picked up

        def drive(tenant, rate, out):
            client = GatewayClient(gw.url, name, tenant=tenant)
            n = int(rate * fair_dur)
            interval = 1.0 / rate

            def one():
                # short deadline: an over-quota request sheds instead of
                # riding retries to success (the abuser stays abusive)
                return one_timed(
                    lambda: client.request(1.0, timeout=0.2)
                ) is not None

            with ThreadPoolExecutor(max_workers=32) as pool:
                t_start = time.perf_counter()
                futs = []
                for i in range(n):
                    target = t_start + i * interval
                    now = time.perf_counter()
                    if target > now:
                        time.sleep(target - now)
                    futs.append(pool.submit(one))
                out[tenant] = sum(f.result() for f in futs)
            client.close()

        def fairness_round(tenant_rates):
            out = {}
            threads = [
                threading.Thread(target=drive, args=(t, r, out), daemon=True)
                for t, r in tenant_rates
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return out

        good = [("good-0", good_rate), ("good-1", good_rate)]
        alone = fairness_round(good)
        contended = fairness_round(good + [("abuser", abuse_quota * 10)])
        good_alone = sum(alone[t] for t, _ in good)
        good_contended = sum(contended[t] for t, _ in good)
        fairness = good_contended / max(good_alone, 1)

        wire_client.close()
        best = max(wire, key=lambda r: r["achieved_qps"])
        top_wire, top_inproc = wire[-1], inproc[-1]
        return {
            "gateway_model": "echo",
            "gateway_replicas": replicas,
            "gateway_echo_delay_ms": delay_ms,
            "gateway_sweep": wire,
            "gateway_inprocess_sweep": inproc,
            "gateway_qps": best["achieved_qps"],
            "gateway_p50_ms": best["p50_ms"],
            "gateway_p99_ms": best["p99_ms"],
            "gateway_inprocess_qps": top_inproc["achieved_qps"],
            "gateway_wire_efficiency": round(
                top_wire["achieved_qps"] / max(top_inproc["achieved_qps"], 1),
                3,
            ),
            "gateway_traced_qps": traced["achieved_qps"],
            "gateway_traced_p99_ms": traced["p99_ms"],
            "gateway_trace_overhead": round(
                1.0 - traced["achieved_qps"]
                / max(top_wire["achieved_qps"], 1.0), 3,
            ),
            "gateway_trace_kept_spans": trace_kept,
            "gateway_trace_spans_dropped": trace_dropped,
            "gateway_fairness_ratio": round(fairness, 3),
            "gateway_served_good_alone": good_alone,
            "gateway_served_good_with_abuser": good_contended,
            "gateway_abuser_served": contended["abuser"],
            "gateway_shed_typed": shed["typed"],
            "gateway_shed_untyped": shed["untyped"],
        }
    finally:
        stop.set()
        gw.shutdown()
        gw.server_close()
        ctrl.controller.shutdown()
        kubelet_mod.LOG_FLUSH_SECONDS = flush0
        sc_mod.AUTOSCALE_PERIOD_S = period0
        obstrace.set_tracer(prev_tracer)


def _chaos_serving_probe(small: bool, full: bool = False):
    """Fault-tolerant serving under chaos (ISSUE 13): an offered-QPS
    load through the REAL gateway onto decode-loop gpt replicas the
    actual controller + kubelet brought up, then a SEEDED kill of
    1-of-3 mid-generation (tests/chaos.ChaosInjector — replayable from
    its seed). Acceptance: ``chaos_failed_requests == 0`` — the kill is
    invisible to a well-formed request because the dispatch loop
    re-routes its mid-flight transport failure to a survivor inside the
    caller's deadline — every failure typed, and ``ejection_time_ms``
    (kill -> the LAST request routed to the corpse) bounded well under
    the passive ``STALE_AFTER_S`` window the health machinery
    preempts."""
    import threading
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    import tfk8s_tpu.runtime.kubelet as kubelet_mod
    import tfk8s_tpu.trainer.serve_controller as sc_mod
    from tfk8s_tpu.api.types import (
        BatchingPolicy,
        ObjectMeta,
        TPUServe,
        TPUServeSpec,
    )
    from tfk8s_tpu.client import FakeClientset
    from tfk8s_tpu.client.store import StoreError
    from tfk8s_tpu.gateway.client import GatewayClient
    from tfk8s_tpu.gateway.router import STALE_AFTER_S
    from tfk8s_tpu.gateway.server import GatewayServer
    from tfk8s_tpu.obs import trace as obstrace
    from tfk8s_tpu.runtime import LocalKubelet
    from tfk8s_tpu.runtime.server import ServeError
    from tfk8s_tpu.trainer import TPUServeController
    from tfk8s_tpu.utils.logging import Metrics

    # the chaos shapes live with the test harness, not the package
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests")
    )
    from chaos import ChaosInjector

    small_mode = small and not full
    if small_mode:
        qps, dur = 25, 3.0
    else:
        qps, dur = 40, 6.0
    replicas, seed = 3, 13
    kill_at_s = dur / 3.0

    flush0 = kubelet_mod.LOG_FLUSH_SECONDS
    period0 = sc_mod.AUTOSCALE_PERIOD_S
    prev_tracer = obstrace.set_tracer(obstrace.Tracer(enabled=False))
    kubelet_mod.LOG_FLUSH_SECONDS = 0.05
    sc_mod.AUTOSCALE_PERIOD_S = 0.1
    cs = FakeClientset()
    ctrl = TPUServeController(cs)
    kubelet = LocalKubelet(cs)
    stop = threading.Event()
    kubelet.run(stop)
    ctrl.run(workers=2, stop=stop, block=False)
    gw = GatewayServer(cs, port=0, metrics=Metrics())
    gw.serve_background()
    name = "bench-chaos"
    try:
        serve = TPUServe(
            metadata=ObjectMeta(name=name),
            spec=TPUServeSpec(
                task="gpt", checkpoint="seed:0", replicas=replicas,
                batching=BatchingPolicy(
                    max_batch_size=8, batch_timeout_ms=2.0, queue_limit=256
                ),
            ),
        )
        serve.spec.template.env.update({
            "TFK8S_SERVE_GPT_SIZE": "tiny",
            "TFK8S_SERVE_GEN_TOKENS": "8",
            "TFK8S_SERVE_PAGE_SIZE": "8",
            "TFK8S_SERVE_MAX_PAGES": "128",
            "TFK8S_SERVE_PREFILL_CHUNK": "16",
        })
        cs.tpuserves().create(serve)

        def wait_ready(n, timeout_s):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                try:
                    if cs.tpuserves().get(name).status.ready_replicas >= n:
                        return True
                except StoreError:
                    pass
                time.sleep(0.05)
            return False

        if not wait_ready(replicas, 120):
            raise RuntimeError("chaos bench replicas never became Ready")

        rng = np.random.default_rng(seed)
        prompts = [
            [int(t) for t in rng.integers(1, 64, size=int(pl))]
            for pl in rng.integers(4, 17, size=32)
        ]
        client = GatewayClient(gw.url, name)
        # compile-warm every replica (least-loaded routing spreads the
        # warm requests as each busy replica's depth rises)
        for _ in range(replicas * 2):
            client.request({"tokens": prompts[0], "gen_tokens": 2},
                           timeout=120)

        failures = {"typed": 0, "untyped": 0}
        lock = threading.Lock()

        def one(i):
            t0 = time.perf_counter()
            try:
                client.request(
                    {"tokens": prompts[i % len(prompts)],
                     "gen_tokens": 4 + i % 5},
                    timeout=15,
                )
                return time.perf_counter() - t0
            except (ServeError, StoreError):
                with lock:
                    failures["typed"] += 1
            except Exception:  # noqa: BLE001 — an UNtyped failure
                with lock:
                    failures["untyped"] += 1
            return None

        # the seeded mid-generation kill, launched with the load
        injector = ChaosInjector(cs, kubelet, seed=seed)
        state = gw.state_for("default", name)
        victim: dict = {}

        def chaos():
            time.sleep(kill_at_s)
            pod = injector.pick_replica(name)
            if pod is None:
                return
            victim["key"] = pod.metadata.key
            victim["t"] = time.monotonic()
            injector.kill_replica(pod)

        chaos_thread = threading.Thread(target=chaos, daemon=True)
        n = int(qps * dur)
        interval = 1.0 / qps
        futs = []
        with ThreadPoolExecutor(max_workers=32) as pool:
            t_start = time.perf_counter()
            chaos_thread.start()
            for i in range(n):
                target = t_start + i * interval
                now = time.perf_counter()
                if target > now:
                    time.sleep(target - now)
                futs.append(pool.submit(one, i))
            results = [f.result() for f in futs]
        chaos_thread.join(timeout=5)

        lat = sorted(r for r in results if r is not None)
        failed = len(results) - len(lat)
        # kill -> the LAST request the router sent to the corpse: the
        # active-discovery bound the passive stale window only backstops
        last = (
            state.table.last_pick_s(victim["key"]) if victim else None
        )
        ejection_ms = (
            round(max(0.0, (last - victim["t"]) * 1000), 1)
            if victim and last is not None else 0.0
        )
        replaced = wait_ready(replicas, 60)
        client.close()
        return {
            "chaos_model": "gpt-tiny",
            "chaos_replicas": replicas,
            "chaos_seed": seed,
            "chaos_offered_qps": qps,
            "chaos_requests": n,
            "chaos_served": len(lat),
            "chaos_failed_requests": failed,
            "chaos_failed_typed": failures["typed"],
            "chaos_failed_untyped": failures["untyped"],
            "chaos_p50_ms": round(lat[len(lat) // 2] * 1000, 3)
            if lat else None,
            "chaos_p99_ms": round(
                lat[min(int(len(lat) * 0.99), len(lat) - 1)] * 1000, 3
            ) if lat else None,
            "chaos_kill_at_s": kill_at_s,
            "chaos_victim": victim.get("key"),
            "ejection_time_ms": ejection_ms,
            "chaos_stale_after_ms": STALE_AFTER_S * 1000,
            "chaos_replica_replaced": replaced,
        }
    finally:
        stop.set()
        gw.shutdown()
        gw.server_close()
        ctrl.controller.shutdown()
        kubelet_mod.LOG_FLUSH_SECONDS = flush0
        sc_mod.AUTOSCALE_PERIOD_S = period0
        obstrace.set_tracer(prev_tracer)


def _gen_serving_probe(small: bool, full: bool = False):
    """Generative serving throughput (ISSUE 7): the continuous-batching
    decode loop (runtime/server.DecodeLoopExecutor — token-granularity
    admit/retire against the block-paged KV cache) vs the slot-per-batch
    baseline (ModelServer + GptGenerator: exact-length buckets, batch dim
    padded with repeated row 0, every request pays the full generation
    budget) under the SAME mixed prompt/output-length open-loop workload.
    Reported per arm: useful generated tokens/s (a request's USEFUL
    tokens are the ``gen_tokens`` it asked for — the baseline's fixed
    over-generation is waste, which is the point) and p50/p99
    time-per-output-token (end-to-end request latency / tokens, the same
    definition both arms). Both arms are compile-warmed over every
    prompt length in the workload first, so the 2x+ is steady-state
    compute, not compile-cache luck."""
    import numpy as np

    from concurrent.futures import ThreadPoolExecutor

    from tfk8s_tpu.runtime.server import (
        DecodeLoopExecutor,
        GptGenerator,
        ModelServer,
        PagedGptDecoder,
    )
    from tfk8s_tpu.utils.logging import Metrics

    small_mode = small and not full
    # Small mode rides the tiny (test-scale) model for a fast signal; the
    # full/issue-artifact run uses the MID serving shape (gpt.mid_config)
    # where a decode step's FLOPs dominate XLA per-op overhead on this
    # CPU host — at tiny scale padded batch rows are nearly free, which
    # understates the baseline's padding/over-generation waste and makes
    # the comparison about dispatch overhead instead of scheduling.
    if small_mode:
        n_requests, size, vocab = 24, "tiny", 64
        slots, page_size, max_pages, chunk = 8, 8, 192, 32
        prompt_lens = tuple(range(4, 40, 3))
        gen_lo, gen_hi = 4, 24
        prefix_len = 16
    else:
        n_requests, size, vocab = 96, "mid", 256
        slots, page_size, max_pages, chunk = 8, 16, 192, 64
        prompt_lens = tuple(range(8, 194, 6))
        gen_lo, gen_hi = 4, 64
        prefix_len = 64
    # arbitrary prompt lengths — real tokenized traffic, and the
    # baseline's documented pathology (exact-length buckets fragment so
    # its batches run mostly-padded). The length set is trimmed to bound
    # the BASELINE arm's warmup, which pays one compile per distinct
    # length (itself part of the pathology, excluded from timing).
    rng = np.random.default_rng(7)
    # half the requests share a page-aligned system prefix — the
    # prefix-cache case; the rest are fully random prompts
    sys_prefix = rng.integers(1, vocab, size=prefix_len).astype(np.int32)

    def prompt_of(pl: int):
        if pl > len(sys_prefix) and rng.random() < 0.5:
            tail = rng.integers(1, vocab, size=pl - len(sys_prefix))
            return np.concatenate([sys_prefix, tail]).astype(np.int32)
        return rng.integers(1, vocab, size=pl).astype(np.int32)

    workload = [
        {
            "tokens": prompt_of(int(pl)),
            "gen_tokens": int(rng.integers(gen_lo, gen_hi + 1)),
        }
        for pl in rng.choice(prompt_lens, size=n_requests)
    ]
    useful = sum(r["gen_tokens"] for r in workload)
    # open-loop pacing fast enough to saturate the loop (the queue is the
    # buffer; queue_limit above n so tokens/s accounting never sheds)
    interval = 0.001

    def run_arm(submit_one, warm):
        warm()
        lat, toks, ttfts = [], [], []
        with ThreadPoolExecutor(max_workers=64) as pool:
            t_start = time.perf_counter()
            futs = []
            for i, r in enumerate(workload):
                target = t_start + i * interval
                now = time.perf_counter()
                if target > now:
                    time.sleep(target - now)
                futs.append(pool.submit(submit_one, r))
            for f in futs:
                lat_s, n_tok, ttft_s = f.result()
                lat.append(lat_s)
                toks.append(n_tok)
                if ttft_s is not None:
                    ttfts.append(ttft_s)
            elapsed = time.perf_counter() - t_start
        tpot = sorted(l / max(t, 1) for l, t in zip(lat, toks))
        out = {
            "tokens_per_s": round(useful / elapsed, 1),
            "wall_s": round(elapsed, 3),
            "tpot_p50_ms": round(tpot[len(tpot) // 2] * 1000, 3),
            "tpot_p99_ms": round(
                tpot[min(int(len(tpot) * 0.99), len(tpot) - 1)] * 1000, 3
            ),
        }
        if ttfts:
            # exact per-request first-token latencies from the reply
            # payload (ISSUE 11) — not bucket-edge approximations
            ttfts.sort()
            out["ttft_p50_ms"] = round(ttfts[len(ttfts) // 2] * 1000, 3)
            out["ttft_p99_ms"] = round(
                ttfts[min(int(len(ttfts) * 0.99), len(ttfts) - 1)] * 1000, 3
            )
        return out

    # -- continuous-batching arm -------------------------------------------
    dec = PagedGptDecoder(
        "seed:0", slots=slots, page_size=page_size, max_pages=max_pages,
        gen_tokens=gen_hi, size=size, prefill_chunk=chunk,
    )
    dec.load()
    loop = DecodeLoopExecutor(
        dec, queue_limit=max(n_requests * 2, 64), metrics=Metrics()
    ).start()
    try:
        def loop_one(r):
            t0 = time.perf_counter()
            out = loop.submit(r, timeout=300)
            return time.perf_counter() - t0, len(out["tokens"]), out.get("ttft_s")

        cb = run_arm(
            loop_one,
            warm=lambda: loop.submit(
                {"tokens": workload[0]["tokens"], "gen_tokens": 2}, timeout=600
            ),
        )
        cb_occupancy = round(loop.mean_batch_occupancy, 2)
        cb_hits = loop.allocator.prefix_hits
    finally:
        loop.drain(timeout=30)

    # -- slot-per-batch baseline -------------------------------------------
    # GptGenerator has ONE generation budget for the whole server; a
    # mixed-output workload pays gen_hi for every request — exactly the
    # slot-holding cost the decode loop retires. Its payloads are bare
    # token arrays (no per-request budget on this path by design).
    base_model = GptGenerator(
        "seed:0", max_batch_size=slots, gen_tokens=gen_hi, size=size
    )
    base_model.load()
    base = ModelServer(
        base_model, max_batch_size=slots, batch_timeout_s=0.002,
        queue_limit=max(n_requests * 2, 64), metrics=Metrics(),
    ).start()
    try:
        def base_one(r):
            t0 = time.perf_counter()
            base.submit(r["tokens"], timeout=600)
            # useful output is what the client ASKED for; the rest of the
            # fixed gen_hi continuation is over-generation. No TTFT: the
            # baseline only replies once the whole batch finishes.
            return time.perf_counter() - t0, r["gen_tokens"], None

        def base_warm():
            # one compile per distinct prompt length — the baseline's
            # per-bucket compile cost, paid before timing for fairness
            for pl in prompt_lens:
                base.submit(
                    np.ones(int(pl), np.int32), timeout=600
                )

        bl = run_arm(base_one, base_warm)
    finally:
        base.drain(timeout=30)

    return {
        "gen_serving_model": f"gpt-{size}",
        "gen_slots": slots,
        "gen_page_size": page_size,
        "gen_max_pages": max_pages,
        "gen_prefill_chunk": chunk,
        "gen_requests": n_requests,
        "gen_prompt_lens": list(prompt_lens),
        "gen_budget_range": [gen_lo, gen_hi],
        "gen_useful_tokens": useful,
        "gen_tokens_per_s": cb["tokens_per_s"],
        "gen_wall_s": cb["wall_s"],
        "tpot_p50_ms": cb["tpot_p50_ms"],
        "tpot_p99_ms": cb["tpot_p99_ms"],
        "ttft_p50_ms": cb.get("ttft_p50_ms"),
        "ttft_p99_ms": cb.get("ttft_p99_ms"),
        "gen_mean_live_slots": cb_occupancy,
        "gen_prefix_cache_hits": cb_hits,
        "gen_tokens_per_s_baseline": bl["tokens_per_s"],
        "gen_wall_s_baseline": bl["wall_s"],
        "tpot_p99_ms_baseline": bl["tpot_p99_ms"],
        "gen_speedup_vs_batch": round(
            cb["tokens_per_s"] / bl["tokens_per_s"], 2
        ) if bl["tokens_per_s"] else None,
    }


def _sched_probe(small: bool, full: bool = False):
    """Token scheduler (ISSUE 15), two claims:

    A) PRIORITY + PREEMPTION: the SAME mixed-priority open-loop workload
    (a flood of priority-0 bulk requests with a sparse stream of
    priority-2 interactive ones) through a FIFO decode loop and a
    priority loop, both on a page pool deliberately too small for every
    bulk row to stay resident. Under FIFO the interactive requests queue
    behind the flood; under the priority scheduler they jump the queue
    and — when their prefill stalls on pages — spill the youngest bulk
    row's KV to the host buffer (``tfk8s_sched_preemptions_total``).
    Reported: per-class p99 TPOT (end-to-end latency / generated tokens,
    queue wait included — that IS the product metric) for both arms,
    preemption count, and the priority arm's aggregate useful tokens/s
    (the scheduler must not buy latency with throughput: in full mode
    this is compared against the recorded ISSUE-7 continuous-batching
    floor, same model scale and slot count).

    B) SPECULATIVE DECODE: a tiny DRAFT and a mid-shaped TARGET are both
    briefly trained on the hermetic affine-chain stream (the draft is
    ~16x cheaper per step but learns the same transition table, so its
    greedy proposals genuinely match the target's picks), then the same
    chain-prompt workload runs through a plain loop and a speculative
    loop (k draft proposals verified in ONE packed target step).
    Reported: tokens/s both arms, the speedup, the realized accept
    ratio, and a token-identity bit (speculative output must equal plain
    output stream-for-stream — draft quality only sets the speedup)."""
    import dataclasses as _dc
    from concurrent.futures import ThreadPoolExecutor

    import jax
    import numpy as np

    from tfk8s_tpu.models import gpt
    from tfk8s_tpu.models.bert import make_chain_tokens
    from tfk8s_tpu.parallel.mesh import make_mesh
    from tfk8s_tpu.runtime.sched import SpeculativeEngine
    from tfk8s_tpu.runtime.server import DecodeLoopExecutor, PagedGptDecoder
    from tfk8s_tpu.runtime.train import TrainConfig, Trainer
    from tfk8s_tpu.utils.logging import Metrics

    small_mode = small and not full
    # aging deliberately long for the measurement window: anti-starvation
    # promotion is a liveness guarantee, not a latency feature, and a
    # seconds-scale bench with product aging (5s) would promote the bulk
    # flood mid-run and blur the very separation being measured
    aging_s = 30.0
    if small_mode:
        # bulk budgets sized so ~2 resident rows fill the 15 usable
        # pages AND the service rate sits below the 1k req/s arrival —
        # without saturation there is no queue and nothing to schedule
        size, vocab = "tiny", 64
        slots, page_size, max_pages, chunk = 4, 8, 16, 16
        n_requests, hi_every = 64, 4
        lo_prompt_lens, gen_lo, gen_hi = (8, 12, 16), 24, 40
        # an interactive request with a real prompt: 4 pages of need, so
        # a packed pool (free < 4) actually stalls it into a preemption
        hi_prompt, hi_gen = 24, 4
    else:
        # the ISSUE-7 model scale and page budget (gpt-mid, 192 pages),
        # but 16 slots and long bulk budgets so the PAGE POOL is the
        # binding resource rather than slots — 16 resident max-budget
        # rows would need 256 pages.  The floor ratio stays honest:
        # same model, same page budget, strictly more slots.
        size, vocab = "mid", 256
        slots, page_size, max_pages, chunk = 16, 16, 192, 64
        n_requests, hi_every = 96, 6
        lo_prompt_lens = tuple(range(64, 194, 6))
        gen_lo, gen_hi = 32, 64
        hi_prompt, hi_gen = 64, 8
    HI = 2
    rng = np.random.default_rng(11)
    workload = []
    for i in range(n_requests):
        if i % hi_every == hi_every - 1:
            pl, gen, pri = hi_prompt, hi_gen, HI
        else:
            pl = int(rng.choice(lo_prompt_lens))
            gen, pri = int(rng.integers(gen_lo, gen_hi + 1)), 0
        workload.append((
            {
                "tokens": rng.integers(1, vocab, size=pl).astype(np.int32),
                "gen_tokens": gen,
            },
            pri,
        ))
    useful = sum(p["gen_tokens"] for p, _ in workload)
    interval = 0.001

    def pctl(xs, q):
        xs = sorted(xs)
        return round(xs[min(int(len(xs) * q), len(xs) - 1)] * 1000, 3)

    hi_need = -(-(hi_prompt + hi_gen) // page_size)

    def warm_spill(loop):
        """Compile-warm the preemption machinery (KV export on spill,
        chunked re-prefill on restore) before the clock starts: full-slot
        bulk rows plus small fillers pack the pool until a high-priority
        arrival cannot admit, forcing one spill. Best-effort — if the
        fillers retire before the pool packs, the first timed preemption
        pays the compile instead."""
        with ThreadPoolExecutor(max_workers=slots + 2) as wpool:
            big = dec.pages_per_slot * page_size
            n_big = min((max_pages - 1) // dec.pages_per_slot, slots - 2)
            bulk = [
                wpool.submit(
                    loop.submit,
                    {"tokens": rng.integers(
                        1, vocab, size=big - 32).astype(np.int32),
                     "gen_tokens": 32},
                    600,
                )
                for _ in range(n_big)
            ]
            bulk += [
                wpool.submit(
                    loop.submit,
                    {"tokens": rng.integers(1, vocab, size=8).astype(
                        np.int32),
                     "gen_tokens": 3 * page_size},
                    600,
                )
                for _ in range(3)
            ]
            deadline = time.perf_counter() + 5.0
            while (loop.allocator.available() >= hi_need
                   and time.perf_counter() < deadline):
                time.sleep(0.002)
            loop.submit(
                {"tokens": np.ones(hi_prompt, np.int32),
                 "gen_tokens": hi_gen},
                timeout=600, priority=HI,
            )
            for b in bulk:
                b.result()

    def run_pri_arm(loop):
        # warm the prefill/decode programs through THIS loop before the
        # clock starts (the decoder is shared across arms, so only the
        # first arm actually compiles)
        loop.submit({"tokens": workload[0][0]["tokens"], "gen_tokens": 2},
                    timeout=600)
        per_class = {0: [], HI: []}
        with ThreadPoolExecutor(max_workers=64) as pool:
            t_start = time.perf_counter()
            futs = []
            for i, (payload, pri) in enumerate(workload):
                target = t_start + i * interval
                now = time.perf_counter()
                if target > now:
                    time.sleep(target - now)

                def one(payload=payload, pri=pri):
                    t0 = time.perf_counter()
                    out = loop.submit(payload, timeout=600, priority=pri)
                    return pri, time.perf_counter() - t0, len(out["tokens"])

                futs.append(pool.submit(one))
            for f in futs:
                pri, lat, ntok = f.result()
                per_class[pri].append(lat / max(ntok, 1))
            elapsed = time.perf_counter() - t_start
        return {
            "hi_p99": pctl(per_class[HI], 0.99),
            "lo_p99": pctl(per_class[0], 0.99),
            "tokens_per_s": round(useful / elapsed, 1),
        }

    dec = PagedGptDecoder(
        "seed:0", slots=slots, page_size=page_size, max_pages=max_pages,
        gen_tokens=gen_hi, size=size, prefill_chunk=chunk,
    )
    dec.load()
    fifo_loop = DecodeLoopExecutor(
        dec, queue_limit=n_requests * 2, metrics=Metrics()
    ).start()
    try:
        fifo = run_pri_arm(fifo_loop)
    finally:
        fifo_loop.drain(timeout=30)
    pri_loop = DecodeLoopExecutor(
        dec, queue_limit=n_requests * 2, metrics=Metrics(),
        sched_policy="priority", preemption=True, aging_s=aging_s,
    ).start()
    try:
        warm_spill(pri_loop)
        warm_preemptions = pri_loop.preempted_total
        pri = run_pri_arm(pri_loop)
        preemptions = pri_loop.preempted_total - warm_preemptions
    finally:
        pri_loop.drain(timeout=30)

    floor = None
    if not small_mode:
        # the ISSUE-7 continuous-batching artifact is the committed
        # throughput floor at this model scale; absent (fresh checkout
        # pruned of artifacts) the ratio is simply not reported
        fp = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_DETAIL_issue7_continuous_batching.json",
        )
        try:
            with open(fp) as f:
                floor = json.load(f)["gen_serving"]["gen_tokens_per_s"]
        except (OSError, KeyError, ValueError):
            floor = None

    # -- speculative half --------------------------------------------------
    mesh = make_mesh(data=jax.device_count())
    if small_mode:
        # mid shape at test vocab: heavy enough that a target step costs
        # real FLOPs next to the tiny draft, shallow enough to learn the
        # chain in ~100 steps. Training runs at seq 48 — the workload
        # never passes position 47, and the shorter sequence keeps the
        # train bill down.
        tgt_cfg = gpt.mid_config(vocab_size=64, max_len=64)
        seq_len, steps, tbatch = 48, 120, 8
        s_slots, s_ps, s_mp, s_chunk = 4, 8, 48, 16
        s_n, s_pl, s_gen = 16, 24, 24
    else:
        tgt_cfg = gpt.mid_config()
        # train seq 128 < max_len 256: positions past the trained range
        # have garbage embeddings, so the workload stays under 128
        seq_len, steps, tbatch = 128, 200, 16
        s_slots, s_ps, s_mp, s_chunk = 8, 16, 192, 64
        s_n, s_pl, s_gen = 32, 64, 48
    draft_cfg = _dc.replace(
        gpt.tiny_config(),
        vocab_size=tgt_cfg.vocab_size, max_len=tgt_cfg.max_len,
    )
    t_train0 = time.perf_counter()

    def train(cfg, lr=3e-3):
        task = gpt.make_task(cfg=cfg, seq_len=seq_len, batch_size=tbatch)
        trainer = Trainer(
            task,
            TrainConfig(steps=steps, learning_rate=lr, log_every=10 ** 6),
            mesh,
        )
        state, history = trainer.fit()
        return state.params, round(
            float(history[-1]["next_token_accuracy"]), 3
        )

    tgt_params, tgt_acc = train(tgt_cfg)
    draft_params, draft_acc = train(draft_cfg)
    train_s = round(time.perf_counter() - t_train0, 1)

    sdec = PagedGptDecoder(
        "trained:sched-target", slots=s_slots, page_size=s_ps,
        max_pages=s_mp, gen_tokens=s_gen, size=size, prefill_chunk=s_chunk,
        cfg=tgt_cfg, params=tgt_params,
    )
    sdec.load()
    rows = make_chain_tokens(rng, s_n, s_pl, tgt_cfg.vocab_size)
    spec_workload = [
        {"tokens": rows[i].astype(np.int32), "gen_tokens": s_gen}
        for i in range(s_n)
    ]

    def run_spec_arm(loop):
        loop.submit({"tokens": rows[0].astype(np.int32), "gen_tokens": 2},
                    timeout=600)
        outs = [None] * s_n
        with ThreadPoolExecutor(max_workers=max(s_n, 8)) as pool:
            t0 = time.perf_counter()
            futs = [
                pool.submit(
                    lambda i=i, r=r: outs.__setitem__(
                        i, list(loop.submit(r, timeout=600)["tokens"])
                    )
                )
                for i, r in enumerate(spec_workload)
            ]
            for f in futs:
                f.result()
            elapsed = time.perf_counter() - t0
        return outs, elapsed

    plain_loop = DecodeLoopExecutor(
        sdec, queue_limit=s_n * 2, metrics=Metrics()
    ).start()
    try:
        plain_out, plain_s = run_spec_arm(plain_loop)
    finally:
        plain_loop.drain(timeout=30)
    engine = SpeculativeEngine.build(sdec, k=4, size="tiny",
                                     params=draft_params)
    spec_loop = DecodeLoopExecutor(
        sdec, queue_limit=s_n * 2, metrics=Metrics(), speculative=engine,
    ).start()
    try:
        spec_out, spec_s = run_spec_arm(spec_loop)
    finally:
        spec_loop.drain(timeout=30)
    spec_useful = s_n * s_gen
    plain_tps = round(spec_useful / plain_s, 1)
    spec_tps = round(spec_useful / spec_s, 1)

    return {
        "sched_model": f"gpt-{size}",
        "sched_requests": n_requests,
        "sched_hi_requests": n_requests // hi_every,
        "sched_aging_s": aging_s,
        "sched_max_pages": max_pages,
        "sched_hi_tpot_p99_ms": pri["hi_p99"],
        "sched_hi_tpot_p99_ms_fifo": fifo["hi_p99"],
        "sched_hi_p99_win": (
            round(fifo["hi_p99"] / pri["hi_p99"], 2) if pri["hi_p99"] else None
        ),
        "sched_lo_tpot_p99_ms": pri["lo_p99"],
        "sched_lo_tpot_p99_ms_fifo": fifo["lo_p99"],
        "sched_preemptions": preemptions,
        "sched_tokens_per_s": pri["tokens_per_s"],
        "sched_tokens_per_s_fifo": fifo["tokens_per_s"],
        "sched_vs_issue7_floor": (
            round(pri["tokens_per_s"] / floor, 3) if floor else None
        ),
        "sched_spec_target": f"gpt-mid(v{tgt_cfg.vocab_size})",
        "sched_spec_draft": f"gpt-tiny(v{tgt_cfg.vocab_size})",
        "sched_spec_k": 4,
        "sched_spec_requests": s_n,
        "sched_plain_tokens_per_s": plain_tps,
        "sched_spec_tokens_per_s": spec_tps,
        "sched_spec_speedup": (
            round(spec_tps / plain_tps, 2) if plain_tps else None
        ),
        "sched_spec_accept_ratio": round(engine.accept_ratio, 3),
        "sched_spec_identical": bool(plain_out == spec_out),
        "sched_target_accuracy": tgt_acc,
        "sched_draft_accuracy": draft_acc,
        "sched_train_s": train_s,
    }


def _disagg_serving_probe(small: bool, full: bool = False):
    """Disaggregated prefill/decode serving (ISSUE 14), two claims:

    A) PREFIX AFFINITY: multi-turn chat sessions whose page-aligned
       history grows every turn, routed to a prefill pool either by the
       real consistent-hash affinity ring (gateway/affinity.py) or by
       depth-only scatter (uniform spread — what least-loaded does to a
       session under uniform load). Reported: prompt tokens each policy
       actually re-prefilled (prompt length minus the replica's cached
       prefix pages, probed via ``allocator.match_prefix`` at the moment
       of routing) and the saved fraction — the driver's
       ``affinity_reprefill_saved`` acceptance key.

    B) BURST ISOLATION: long-lived decode streams share a plane with a
       burst of long-prompt admissions. Split pools (prefill replica +
       decode replica, KV page handoff across the seam) keep the burst's
       chunked prefill off the decode loop — the streams' p99 TPOT is
       compared against a shared pool of the SAME total replica count
       where burst prefill chunks interleave with live decode steps.

    Both parts run the real executors end-to-end (submit_prefill ->
    LocalKVTransport -> submit_handoff), so every affinity-arm number
    already pays the handoff serialize/verify/import tax."""
    import numpy as np

    from concurrent.futures import ThreadPoolExecutor

    from tfk8s_tpu.gateway.affinity import AffinityRing, affinity_key_of
    from tfk8s_tpu.runtime.handoff import LocalKVTransport
    from tfk8s_tpu.runtime.server import DecodeLoopExecutor, PagedGptDecoder
    from tfk8s_tpu.utils.logging import Metrics

    small_mode = small and not full
    # Page geometry note: the prefix cache only publishes pages covering
    # a PROPER prefix of the prompt (the final token is always re-run),
    # so every turn re-prefills its growth plus one page — a small page
    # relative to the turn growth keeps the affine arm's floor low.
    if small_mode:
        size, vocab = "tiny", 64
        slots, page_size, max_pages, chunk = 8, 4, 1024, 8
        n_sessions, turns, prefix_len = 6, 5, 24
        turn_gen, user_len = 4, 4        # +8/turn: stays page-aligned
        live_n, live_len, live_gen = 6, 8, 48
        burst_n, burst_len, burst_gen = 48, 56, 2
        burst_pace_s = 0.002
    else:
        size, vocab = "mid", 256
        slots, page_size, max_pages, chunk = 8, 8, 1024, 16
        n_sessions, turns, prefix_len = 8, 8, 48
        turn_gen, user_len = 8, 8        # +16/turn: stays page-aligned
        live_n, live_len, live_gen = 6, 16, 96
        burst_n, burst_len, burst_gen = 32, 96, 2
        burst_pace_s = 0.02
    n_prefill = 4
    rounds = 3

    def mk():
        dec = PagedGptDecoder(
            "seed:0", slots=slots, page_size=page_size, max_pages=max_pages,
            gen_tokens=live_gen, size=size, prefill_chunk=chunk,
        )
        dec.load()
        return DecodeLoopExecutor(
            dec, queue_limit=128, metrics=Metrics()
        ).start()

    names = [f"p{i}" for i in range(n_prefill)]
    prefills = {n: mk() for n in names}
    decode = mk()
    transport = LocalKVTransport()
    ring = AffinityRing()
    for n in names:
        ring.add(n)
    handoff_ns = {"n": 0, "bytes": 0, "s": 0.0}

    def two_phase(prefill_ex, payload, timeout=600.0):
        pre = prefill_ex.submit_prefill(payload, timeout=timeout)
        t0 = time.perf_counter()
        moved, nbytes = transport.transfer(pre["handoff"])
        handoff_ns["s"] += time.perf_counter() - t0
        handoff_ns["n"] += 1
        handoff_ns["bytes"] += nbytes
        return decode.submit_handoff(moved, timeout=timeout)

    try:
        # -- part A: re-prefilled tokens, affinity vs scatter --------------
        # Distinct session content per arm so the shared executors' prefix
        # caches can't leak one arm's pages into the other.
        def run_sessions(pick, seed_base):
            prefilled = 0
            for s in range(n_sessions):
                rng = np.random.default_rng(seed_base + s)
                hist = rng.integers(1, vocab, size=prefix_len).astype(np.int32)
                for t in range(turns):
                    ex = prefills[pick(s, t, hist)]
                    _pages, cached_tok = ex.allocator.match_prefix(hist)
                    prefilled += len(hist) - cached_tok
                    out = two_phase(
                        ex, {"tokens": hist, "gen_tokens": turn_gen}
                    )
                    user = rng.integers(1, vocab, size=user_len)
                    hist = np.concatenate([
                        hist, np.asarray(out["tokens"], np.int32),
                        user.astype(np.int32),
                    ])
            return prefilled

        scatter_prefilled = run_sessions(
            lambda s, t, hist: names[(s + t) % n_prefill], 1000
        )
        affine_prefilled = run_sessions(
            lambda s, t, hist: ring.owner(
                affinity_key_of(hist, page_size)
            ), 2000
        )
        saved = (
            round(1.0 - affine_prefilled / scatter_prefilled, 3)
            if scatter_prefilled else None
        )

        # -- part B: live-stream decode TPOT under a prompt burst ----------
        # Equal replica counts per arm: disagg = 1 prefill + 1 decode,
        # shared = 2 do-everything replicas. Fresh random prompts every
        # round so neither part A's pages nor the previous round's can
        # subsidize an arm. TPOT is the DECODE-phase cadence in both
        # arms — time after the first token over the remaining tokens —
        # so prefill-queue wait (a TTFT cost by construction) can't
        # contaminate the cadence comparison. The burst is OPEN-LOOP
        # paced (real arrivals, not an instantaneous dump), and the
        # whole comparison runs under one shortened GIL switch interval:
        # at the default 5 ms slice a saturated sibling thread quantizes
        # every cross-thread step handoff to the slice length on the
        # 1-core box, drowning both arms in scheduler noise. Arms
        # interleave across rounds; the median round is reported.
        rng = np.random.default_rng(3000)
        settle_s = 0.01

        def tpot_arm(live_one, burst_one):
            live_prompts = [
                rng.integers(1, vocab, size=live_len).astype(np.int32)
                for _ in range(live_n)
            ]
            burst_prompts = [
                rng.integers(1, vocab, size=burst_len).astype(np.int32)
                for _ in range(burst_n)
            ]
            with ThreadPoolExecutor(max_workers=live_n + burst_n) as pool:
                t0 = time.perf_counter()
                live = [
                    pool.submit(live_one, p) for p in live_prompts
                ]
                # just long enough for the streams to admit — the burst
                # must land while they are mid-generation
                time.sleep(settle_s)
                tb = time.perf_counter()
                burst = []
                for i, p in enumerate(burst_prompts):
                    target = tb + i * burst_pace_s
                    now = time.perf_counter()
                    if target > now:
                        time.sleep(target - now)
                    burst.append(pool.submit(burst_one, i, p))
                for f in burst:
                    f.result()
                tpots = sorted(f.result() for f in live)
                wall = time.perf_counter() - t0
            return {
                "tpot_p50_ms": round(tpots[len(tpots) // 2] * 1000, 3),
                "tpot_p99_ms": round(tpots[-1] * 1000, 3),
                "wall_s": round(wall, 3),
            }

        def disagg_live(p):
            # decode cadence = handoff-admission to retirement over the
            # locally generated tokens (the first came from prefill)
            pre = prefills["p0"].submit_prefill(
                {"tokens": p, "gen_tokens": live_gen}, timeout=600
            )
            moved, _nb = transport.transfer(pre["handoff"])
            t0 = time.perf_counter()
            decode.submit_handoff(moved, timeout=600)
            return (time.perf_counter() - t0) / (live_gen - 1)

        def disagg_burst(_i, p):
            two_phase(prefills["p0"], {"tokens": p, "gen_tokens": burst_gen})

        shared = [prefills["p1"], prefills["p2"]]
        live_rr = {"i": 0}

        def shared_live(p):
            ex = shared[live_rr["i"] % 2]
            live_rr["i"] += 1
            t0 = time.perf_counter()
            out = ex.submit(
                {"tokens": p, "gen_tokens": live_gen}, timeout=600
            )
            lat = time.perf_counter() - t0
            ttft = out.get("ttft_s") or 0.0
            return (lat - ttft) / (live_gen - 1)

        def shared_burst(i, p):
            shared[i % 2].submit(
                {"tokens": p, "gen_tokens": burst_gen}, timeout=600
            )

        # compile-warm every shape on every replica before timing
        for plen in (live_len, burst_len):
            two_phase(prefills["p0"], {
                "tokens": np.ones(plen, np.int32), "gen_tokens": 2,
            })
            for ex in shared:
                ex.submit({
                    "tokens": np.ones(plen, np.int32), "gen_tokens": 2,
                }, timeout=600)

        import sys as _sys

        old_switch = _sys.getswitchinterval()
        _sys.setswitchinterval(0.0005)
        try:
            sh_rounds, dg_rounds = [], []
            for _ in range(rounds):
                sh_rounds.append(tpot_arm(shared_live, shared_burst))
                dg_rounds.append(tpot_arm(disagg_live, disagg_burst))
        finally:
            _sys.setswitchinterval(old_switch)

        def med(rs, key):
            vals = sorted(r[key] for r in rs)
            return vals[len(vals) // 2]

        sh = {k: med(sh_rounds, k) for k in sh_rounds[0]}
        dg = {k: med(dg_rounds, k) for k in dg_rounds[0]}
    finally:
        for ex in list(prefills.values()) + [decode]:
            ex.drain(timeout=30)

    return {
        "disagg_model": f"gpt-{size}",
        "disagg_page_size": page_size,
        "disagg_prefill_chunk": chunk,
        "disagg_prefill_replicas": n_prefill,
        "disagg_decode_replicas": 1,
        "disagg_sessions": n_sessions,
        "disagg_turns": turns,
        "disagg_prefix_tokens": prefix_len,
        "scatter_prefilled_tokens": int(scatter_prefilled),
        "affinity_prefilled_tokens": int(affine_prefilled),
        "affinity_reprefill_saved": saved,
        "disagg_handoffs": handoff_ns["n"],
        "disagg_handoff_bytes_mean": (
            int(handoff_ns["bytes"] / handoff_ns["n"]) if handoff_ns["n"]
            else None
        ),
        "disagg_handoff_ms_mean": (
            round(handoff_ns["s"] / handoff_ns["n"] * 1000, 3)
            if handoff_ns["n"] else None
        ),
        "disagg_live_streams": live_n,
        "disagg_live_gen_tokens": live_gen,
        "disagg_burst_requests": burst_n,
        "disagg_burst_prompt_tokens": burst_len,
        "disagg_tpot_p50_ms": dg["tpot_p50_ms"],
        "disagg_tpot_p99_ms": dg["tpot_p99_ms"],
        "disagg_burst_wall_s": dg["wall_s"],
        "shared_tpot_p50_ms": sh["tpot_p50_ms"],
        "shared_tpot_p99_ms": sh["tpot_p99_ms"],
        "shared_burst_wall_s": sh["wall_s"],
        "disagg_tpot_win": (
            round(sh["tpot_p99_ms"] / dg["tpot_p99_ms"], 2)
            if dg["tpot_p99_ms"] else None
        ),
    }


def _kv_economy_probe(small: bool, full: bool = False):
    """Global KV economy (ISSUE 17), two claims:

    A) HOST TIER: a many-session round-robin whose working set of
       prefixes overflows the tight device page pool, so every revisit
       finds its pages already evicted from device. With the host tier
       on, ``_evict_idle`` DEMOTED them to host RAM and the revisit
       restores through the handoff-import path; with it off (the PR 14
       baseline behavior) the revisit re-prefills from scratch. Both
       arms run identical prompts on identical executors and count the
       tokens the prefill loop actually computed (prompt length minus
       the lease's cached pages, hooked at ``allocator.admit`` — the
       exact quantity the chunked-prefill loop skips). Reported:
       ``kv_reprefill_saved`` — the driver's acceptance key, judged
       against the PR 14 affinity baseline of 0.6.

    B) PEER TIER: replica A is warm with N distinct long prompts;
       cold replica B submits the same prompts with a ``kv_peer`` hint
       (what the gateway's cache directory supplies) and pulls the
       prefix over the KV transport, while equally-cold replica C
       re-prefills them. TTFT p99 of the hinted pulls vs the
       re-prefills — every fetch pays the full export/serialize/
       round-trip/verify/import tax.

    Both parts also assert the economy's core contract inline: tiered
    and flat arms must emit IDENTICAL tokens (a restore or fetch that
    changed the stream would be a correctness bug, not a perf win)."""
    import numpy as np

    from tfk8s_tpu.runtime.handoff import LocalKVTransport
    from tfk8s_tpu.runtime.server import DecodeLoopExecutor, PagedGptDecoder
    from tfk8s_tpu.utils.logging import Metrics

    small_mode = small and not full
    # Geometry notes: the host working set must OVERFLOW the device pool
    # (sessions * idle chain pages > max_pages) so round-robin revisits
    # always miss device, while the pool still holds the largest single
    # lease. The peer pool must NOT overflow (A keeps every prompt warm).
    if small_mode:
        size, vocab = "tiny", 64
        slots, page_size, chunk, gen = 8, 4, 4, 4
        host_sessions, host_rounds, host_prefix = 12, 5, 40
        host_max_pages, host_bytes = 64, 32 << 20
        peer_prompts, peer_prefix, peer_max_pages = 24, 56, 512
    else:
        size, vocab = "mid", 256
        slots, page_size, chunk, gen = 8, 8, 16, 8
        host_sessions, host_rounds, host_prefix = 12, 5, 96
        host_max_pages, host_bytes = 96, 256 << 20
        peer_prompts, peer_prefix, peer_max_pages = 16, 192, 512

    def mk(max_pages, host_b=0, peer_registry=None):
        dec = PagedGptDecoder(
            "seed:0", slots=slots, page_size=page_size, max_pages=max_pages,
            gen_tokens=gen, size=size, prefill_chunk=chunk,
        )
        dec.load()
        kwargs = {}
        if peer_registry is not None:
            kwargs = dict(
                kv_peer_fetch=True,
                kv_transport=LocalKVTransport(),
                kv_peer_resolve=peer_registry.get,
            )
        return DecodeLoopExecutor(
            dec, queue_limit=128, metrics=Metrics(),
            kv_host_bytes=host_b, **kwargs,
        ).start()

    def count_prefilled(ex):
        # hook admit: the chunked-prefill loop starts each request at
        # lease.cached_pages * page_size, so plen minus that is exactly
        # the token count it computes — device hits AND host restores
        # (which land as cached pages before admit) both shrink it
        counter = {"tokens": 0}
        orig = ex.allocator.admit

        def admit(tokens, gen_budget):
            lease = orig(tokens, gen_budget)
            counter["tokens"] += max(
                0, len(tokens) - lease.cached_pages * page_size
            )
            return lease

        ex.allocator.admit = admit
        return counter

    def p(vals, q):
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1, int(q * len(vals)))] * 1000, 3)

    # -- part A: re-prefilled tokens, host tier on vs off ----------------
    rng = np.random.default_rng(1700)
    host_prompts = [
        rng.integers(1, vocab, size=host_prefix).astype(np.int32)
        for _ in range(host_sessions)
    ]
    tiered = mk(host_max_pages, host_b=host_bytes)
    flat = mk(host_max_pages)
    restore_ttft, reprefill_ttft = [], []
    identical = True
    try:
        # Compile-warm both executors before the counters go in — and
        # warm the whole demote->restore path (the KV gather/scatter
        # programs jit on first use): overflow the device pool with
        # throwaway prompts so the first one demotes, then revisit it to
        # force a restore. Symmetric submits keep the arms comparable.
        warmups = [
            np.full(host_prefix, v, np.int32)
            for v in range(1, 2 + host_max_pages * page_size // host_prefix)
        ]
        for w in warmups + [warmups[0]]:
            payload = {"tokens": w, "gen_tokens": gen}
            tiered.submit(dict(payload), timeout=600)
            flat.submit(dict(payload), timeout=600)
        tiered_n, flat_n = count_prefilled(tiered), count_prefilled(flat)
        for r in range(host_rounds):
            for s in range(host_sessions):
                payload = {"tokens": host_prompts[s], "gen_tokens": gen}
                out_t = tiered.submit(dict(payload), timeout=600)
                out_f = flat.submit(dict(payload), timeout=600)
                identical = identical and (
                    list(out_t["tokens"]) == list(out_f["tokens"])
                )
                if r:  # revisits only: round 0 is the cold fill
                    restore_ttft.append(out_t["ttft_s"])
                    reprefill_ttft.append(out_f["ttft_s"])
        demotions = tiered.metrics.get_counter(
            "tfk8s_serving_kv_host_ops_total", {"op": "demote"}
        ) or 0
        restores = tiered.metrics.get_counter(
            "tfk8s_serving_kv_host_ops_total", {"op": "restore"}
        ) or 0
    finally:
        tiered.drain(timeout=30)
        flat.drain(timeout=30)
    saved = (
        round(1.0 - tiered_n["tokens"] / flat_n["tokens"], 3)
        if flat_n["tokens"] else None
    )

    # -- part B: peer-fetch TTFT vs re-prefill TTFT ----------------------
    registry = {}
    warm_peer = mk(peer_max_pages)
    registry["A"] = warm_peer
    puller = mk(peer_max_pages, peer_registry=registry)
    cold = mk(peer_max_pages)
    rng = np.random.default_rng(1701)
    peer_prompts_arr = [
        rng.integers(1, vocab, size=peer_prefix).astype(np.int32)
        for _ in range(peer_prompts)
    ]
    fetch_ttft, prefill_ttft = [], []
    peer_identical = True
    try:
        for prompt in peer_prompts_arr:  # warm A with every prompt
            warm_peer.submit(
                {"tokens": prompt, "gen_tokens": gen}, timeout=600
            )
        # compile-warm on a throwaway prompt: the HINTED submit also
        # jits A's export gather and B's import scatter off the clock
        warm = {"tokens": np.ones(peer_prefix, np.int32), "gen_tokens": gen}
        warm_peer.submit(dict(warm), timeout=600)
        puller.submit(dict(warm), timeout=600, kv_peer="A")
        cold.submit(dict(warm), timeout=600)    # no hint: plain prefill
        fetches0 = puller.metrics.get_counter(
            "tfk8s_serving_kv_peer_fetches_total", {"outcome": "ok"}
        ) or 0
        for prompt in peer_prompts_arr:
            out_b = puller.submit(
                {"tokens": prompt, "gen_tokens": gen},
                timeout=600, kv_peer="A",
            )
            out_c = cold.submit(
                {"tokens": prompt, "gen_tokens": gen}, timeout=600
            )
            peer_identical = peer_identical and (
                list(out_b["tokens"]) == list(out_c["tokens"])
            )
            fetch_ttft.append(out_b["ttft_s"])
            prefill_ttft.append(out_c["ttft_s"])
        fetches_ok = (puller.metrics.get_counter(
            "tfk8s_serving_kv_peer_fetches_total", {"outcome": "ok"}
        ) or 0) - fetches0
    finally:
        for ex in (warm_peer, puller, cold):
            ex.drain(timeout=30)

    fetch_p99 = p(fetch_ttft, 0.99)
    prefill_p99 = p(prefill_ttft, 0.99)
    return {
        "kv_model": f"gpt-{size}",
        "kv_page_size": page_size,
        "kv_prefill_chunk": chunk,
        "kv_host_bytes": host_bytes,
        "kv_host_sessions": host_sessions,
        "kv_host_rounds": host_rounds,
        "kv_host_prefix_tokens": host_prefix,
        "kv_host_device_pages": host_max_pages,
        "kv_tiered_prefilled_tokens": int(tiered_n["tokens"]),
        "kv_flat_prefilled_tokens": int(flat_n["tokens"]),
        "kv_reprefill_saved": saved,
        "kv_host_demotions": int(demotions),
        "kv_host_restores": int(restores),
        "kv_host_restore_p50_ms": p(restore_ttft, 0.5),
        "kv_host_restore_p99_ms": p(restore_ttft, 0.99),
        "kv_host_reprefill_p50_ms": p(reprefill_ttft, 0.5),
        "kv_host_reprefill_p99_ms": p(reprefill_ttft, 0.99),
        "kv_restore_identical": bool(identical),
        "kv_peer_prompts": peer_prompts,
        "kv_peer_prefix_tokens": peer_prefix,
        "kv_peer_fetches_ok": int(fetches_ok),
        "kv_peer_fetch_p50_ms": p(fetch_ttft, 0.5),
        "kv_peer_fetch_p99_ms": fetch_p99,
        "kv_peer_reprefill_p50_ms": p(prefill_ttft, 0.5),
        "kv_peer_reprefill_p99_ms": prefill_p99,
        "kv_peer_fetch_identical": bool(peer_identical),
        "kv_peer_ttft_win": (
            round(prefill_p99 / fetch_p99, 2) if fetch_p99 else None
        ),
    }


def _recovery_probe(small: bool, full: bool = False):
    """Elastic recovery time (ISSUE 6): kill 1 of 4 workers mid-epoch
    with a reclaim notice against the REAL job controller + hermetic
    kubelet, and time reclaim-delivery -> first post-resize optimizer
    step observed on the control plane. Repeated rounds (the gang scales
    back up between kills) give p50/p99 — the number that shows a
    reclaim costs seconds of resize, not minutes of whole-gang
    restart-from-checkpoint. The drain checkpoint is what the resized
    world resumes from, so lost work is bounded by one step, not by the
    periodic save interval. Hermetic and chip-free, like the
    control-plane block."""
    import shutil
    import tempfile
    import threading

    import tfk8s_tpu.runtime.kubelet as kubelet_mod
    from tfk8s_tpu.api import helpers
    from tfk8s_tpu.api.types import (
        ContainerSpec, ElasticPolicy, JobConditionType, ObjectMeta, PodPhase,
        ReplicaSpec, ReplicaType, RunPolicy, SchedulingPolicy, TPUJob,
        TPUJobSpec, TPUSpec,
    )
    from tfk8s_tpu.client import FakeClientset, NotFound
    from tfk8s_tpu.runtime import LocalKubelet, registry
    from tfk8s_tpu.trainer import SliceAllocator, TPUJobController
    from tfk8s_tpu.trainer import labels as L
    from tfk8s_tpu.trainer.replicas import CHECKPOINT_DIR_ANNOTATION

    rounds = 5 if (full or not small) else 2
    workers, min_r, ckpt_every, log_every = 4, 2, 500, 5

    def train(env, stop):
        import dataclasses as _dc

        from tfk8s_tpu.models import mlp
        from tfk8s_tpu.runtime.launcher import ProcessContext
        from tfk8s_tpu.runtime.train import run_task

        env = dict(env)
        if ProcessContext.from_env(env).process_id != 0:
            env.pop("TFK8S_CHECKPOINT_DIR", None)  # one checkpoint writer
        run_task(_dc.replace(mlp.make_task(), targets={}), env, stop)

    registry.register("bench.recovery.train", train)

    def wait(cond, timeout_s, period=0.02):
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if cond():
                return True
            time.sleep(period)
        return False

    old_flush = kubelet_mod.LOG_FLUSH_SECONDS
    ckpt_dir = tempfile.mkdtemp(prefix="bench-recovery-")
    cs = FakeClientset()
    ctrl = TPUJobController(cs, allocator=SliceAllocator({"cpu-1": 2}))
    kubelet = LocalKubelet(cs)
    stop = threading.Event()
    name = "bench-recovery"
    try:
        # inside the try: a setup failure must still restore the flush
        # period and stop the agents, or it pollutes every later block
        kubelet_mod.LOG_FLUSH_SECONDS = 0.05
        kubelet.run(stop)
        if not ctrl.run(workers=2, stop=stop, block=False):
            raise RuntimeError("controller failed to start")
        cs.tpujobs().create(TPUJob(
            metadata=ObjectMeta(
                name=name, annotations={CHECKPOINT_DIR_ANNOTATION: ckpt_dir}
            ),
            spec=TPUJobSpec(
                replica_specs={ReplicaType.WORKER: ReplicaSpec(
                    replicas=workers,
                    template=ContainerSpec(
                        entrypoint="bench.recovery.train",
                        env={
                            "TFK8S_TRAIN_STEPS": "10000000",
                            "TFK8S_CHECKPOINT_EVERY": str(ckpt_every),
                            "TFK8S_LOG_EVERY": str(log_every),
                        },
                    ),
                )},
                tpu=TPUSpec(accelerator="cpu-1"),
                run_policy=RunPolicy(
                    backoff_limit=3,
                    scheduling=SchedulingPolicy(gang=True),
                    elastic=ElasticPolicy(
                        min_replicas=min_r, max_replicas=workers,
                        # long enough that the resized world's first
                        # progress report provably lands BEFORE the
                        # debounced scale-up re-forms the gang again
                        # (pads round wall-clock, never the sample)
                        resize_debounce_s=3.0,
                    ),
                ),
            ),
        ))

        def live_workers():
            pods, _rv = cs.pods().list(label_selector=L.job_selector(name))
            return [
                p for p in pods
                if p.metadata.deletion_timestamp is None
                and p.metadata.labels.get(L.REPLICA_TYPE) == "Worker"
            ]

        def world_step(min_wv):
            """Freshest reported optimizer step among RUNNING pods whose
            world version is at least ``min_wv`` (0 when none reported
            yet)."""
            return max(
                (
                    p.status.training.get("step", 0)
                    for p in live_workers()
                    if p.status.phase == PodPhase.RUNNING
                    and int(
                        p.spec.containers[0].env.get("TFK8S_WORLD_VERSION", "0")
                    ) >= min_wv
                ),
                default=0,
            )

        def status():
            return cs.tpujobs().get(name).status

        def at_full_size():
            st = status()
            return (
                st.elastic_replicas is None
                and helpers.has_condition(st, JobConditionType.RUNNING)
                and len(live_workers()) == workers
                and world_step(st.world_version) > 0
            )

        if not wait(at_full_size, 180):
            raise RuntimeError("elastic job never reached steady state")

        samples = []
        for _ in range(rounds):
            wv = status().world_version
            pre_step = world_step(wv)
            victim = sorted(
                (
                    p for p in live_workers()
                    if p.status.phase == PodPhase.RUNNING
                    and not p.metadata.name.endswith("-0")
                ),
                key=lambda p: p.metadata.name,
            )[-1]
            t0 = time.perf_counter()
            kubelet.deliver_reclaim(victim.metadata.key, grace_s=5.0)
            # recovered = a RE-FORMED world (no whole-gang restart: the
            # backoff budget is asserted untouched below) has run
            # optimizer steps past the pre-kill frontier
            if not wait(lambda: world_step(wv + 1) > pre_step, 120):
                raise RuntimeError(
                    f"no world past v{wv} resumed beyond step {pre_step}"
                )
            samples.append(time.perf_counter() - t0)
            # capacity "returns": wait out the debounced scale-up so the
            # next round kills 1 of 4 again
            if not wait(
                lambda: status().world_version > wv + 1 and at_full_size(), 120
            ):
                raise RuntimeError(
                    f"scale-up past world v{wv + 1} never landed"
                )

        st = status()
        burned = st.gang_restarts
        snap = ctrl.metrics.snapshot()["histograms"]
        drain = next(
            (
                v for k, v in snap.items()
                if k.startswith("tfk8s_drain_checkpoint_seconds")
            ),
            None,
        )
    finally:
        try:
            cs.tpujobs().delete(name)
        except NotFound:
            pass
        # let pod threads leave JAX before teardown (exit mid-dispatch
        # aborts the interpreter), then stop the agents
        wait(lambda: not kubelet._claimed, 60, period=0.1)
        stop.set()
        ctrl.controller.shutdown()
        kubelet_mod.LOG_FLUSH_SECONDS = old_flush
        shutil.rmtree(ckpt_dir, ignore_errors=True)

    ordered = sorted(samples)
    return {
        "recovery_workers": workers,
        "recovery_min_replicas": min_r,
        "recovery_rounds": rounds,
        "recovery_samples_s": [round(s, 3) for s in samples],
        "recovery_p50_s": round(ordered[len(ordered) // 2], 3),
        "recovery_p99_s": round(
            ordered[min(int(len(ordered) * 0.99), len(ordered) - 1)], 3
        ),
        # resizes must never burn the restart budget — a nonzero value
        # here means the legacy whole-gang path fired
        "recovery_backoff_burned": burned,
        # the periodic save interval (in steps) the drain checkpoint
        # beats: resume loses at most the in-flight step, not up to
        # ckpt_every steps of replay
        "recovery_checkpoint_every_steps": ckpt_every,
        **(
            {
                "recovery_drain_checkpoint_mean_s": round(
                    drain["sum"] / drain["count"], 3
                ),
                "recovery_drain_checkpoints": drain["count"],
            }
            if drain and drain["count"]
            else {}
        ),
    }


_PROBE_CODE = """
import os
if os.environ.get("BENCH_PLATFORM"):
    from tfk8s_tpu.runtime.launcher import force_platform
    force_platform(os.environ["BENCH_PLATFORM"])
import jax
jax.devices()
"""


def _probe_backend(timeout_s: float) -> None:
    """Fail FAST (rc=1 with a reason) when the accelerator backend is
    unreachable — jax.devices() can hang indefinitely when the remote
    tunnel is down, which would wedge the driver instead of reporting."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    # APPEND to any existing PYTHONPATH — on this rig it carries the
    # remote-TPU plugin's sitecustomize; clobbering it would probe a
    # different backend than the bench uses.
    pp = os.environ.get("PYTHONPATH", "")
    pp = f"{repo}{os.pathsep}{pp}" if pp else repo
    try:
        subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            timeout=timeout_s,
            check=True,
            capture_output=True,
            env={**os.environ, "PYTHONPATH": pp},
        )
    except subprocess.TimeoutExpired:
        print(
            f"bench: accelerator backend unreachable (probe timed out "
            f"after {timeout_s:.0f}s — remote tunnel down?)",
            file=sys.stderr,
        )
        raise SystemExit(1)
    except subprocess.CalledProcessError as exc:
        print(
            "bench: backend init failed:\n"
            + exc.stderr.decode(errors="replace")[-2000:],
            file=sys.stderr,
        )
        raise SystemExit(1)


def main() -> None:
    # CPU runs can't hang on a dead tunnel — skip the (double-init) probe
    if os.environ.get("BENCH_PLATFORM") != "cpu":
        _probe_backend(float(os.environ.get("BENCH_PROBE_TIMEOUT", "300")))
    if "--roofline" in sys.argv:
        # the committed platform-envelope harness (tools/roofline.py):
        # matmul TF/s, streaming GB/s, Pallas DMA, ResNet decomposition.
        # Runs AFTER the backend probe — a dead tunnel must time out, not
        # hang the first jax.devices() call.
        from tools import roofline

        roofline.main()
        return
    if os.environ.get("BENCH_PLATFORM"):
        # e.g. BENCH_PLATFORM=cpu for the hermetic smoke test — env vars
        # alone don't switch platforms here (sitecustomize imports jax at
        # interpreter startup), so go through the launcher's latch-aware
        # switch before the first backend query below.
        from tfk8s_tpu.runtime.launcher import force_platform

        force_platform(os.environ["BENCH_PLATFORM"])
    import jax

    from tfk8s_tpu.models import bert, resnet
    from tfk8s_tpu.parallel.mesh import make_mesh

    small = os.environ.get("BENCH_SMALL") == "1"
    n_chips = jax.device_count()
    mesh = make_mesh(data=n_chips)

    # -- headline: ResNet-50 images/sec/chip --------------------------------
    if small:
        rn_task = resnet.make_task(
            depth=18, num_classes=8, image_size=32, batch_size=8, width=8
        )
        steps = 8
    else:
        rn_task = resnet.make_task(
            depth=50,
            num_classes=1000,
            image_size=224,
            batch_size=int(os.environ.get("BENCH_BATCH", "256")),
        )
        steps = 30
    sec_per_step, rn_windows = _time_task(rn_task, mesh, steps)
    value = rn_task.batch_size / sec_per_step / n_chips

    # -- secondary: BERT-base MLM step-time (BASELINE.md row 2) -------------
    if small:
        bert_seq = 32
        bert_task = bert.make_task(
            cfg=bert.tiny_config(), seq_len=bert_seq, batch_size=8
        )
        bsteps = 8
    else:
        bert_seq = int(os.environ.get("BENCH_BERT_SEQ", "128"))
        bert_task = bert.make_task(
            cfg=bert.base_config(),
            seq_len=bert_seq,
            batch_size=int(os.environ.get("BENCH_BERT_BATCH", "64")),
        )
        bsteps = 50
    bert_sec, bert_windows = _time_task(bert_task, mesh, bsteps)

    # -- the PRODUCT loop: Trainer.fit with its prefetch pipeline must
    # agree with the scanned number (VERDICT r2 next #3). Measured on
    # BERT: its per-step host batch is ~64 KB, so the remote tunnel's
    # ~10 MB/s host->device link (which makes a per-step 154 MB ResNet
    # batch physically untimeable here — seconds per transfer; see
    # PERF_RESNET.md) stays off the critical path. The CPU-mesh test
    # tests/test_train_runtime.py covers the ResNet-shaped agreement.
    # OPTIONAL sections from here on degrade gracefully: a transient
    # tunnel failure (remote_compile connection drops have been observed
    # mid-run) must cost its rows, not the whole headline artifact.
    degraded = []
    fit_sec = None
    fit_windows: list = []
    try:
        fit_sec, fit_windows = _fit_step_time(bert_task, mesh, 12 if small else 30)
    except Exception as exc:  # noqa: BLE001
        print(f"bench: fit row failed: {exc}", file=sys.stderr)
        degraded.append("fit")
    # the host-loop chunking row (TFK8S_SCAN_STEPS=8). Measurement
    # history worth keeping: single-window runs of this row read 1.8-2.1x
    # (78-85 ms/step) and looked like a tunnel negative — median-of-3
    # shows ~1.11x (45.5 ms/step), i.e. the outliers were transient
    # tunnel stalls landing in the one timed window, the same failure
    # mode that once put the UNCHUNKED fit row at 7.7x. Chunking through
    # the tunnel is roughly throughput-neutral here (it wins on local
    # runtimes by amortizing dispatch; the tunnel's async enqueue is
    # already cheap at ~0.1 ms/step).
    fit8_sec = None
    fit8_windows: list = []
    try:
        fit8_sec, fit8_windows = _fit_step_time(
            bert_task, mesh, 15 if small else 31, scan_steps=8
        )
    except Exception as exc:  # noqa: BLE001
        print(f"bench: fit scan8 row failed: {exc}", file=sys.stderr)
        degraded.append("fit_scan8")

    # measured per-step tunnel costs bounding the fit-vs-scanned gap
    try:
        rtt_s, enq_s, h2d_s, batch_bytes = _tunnel_probes(bert_task, mesh)
    except Exception as exc:  # noqa: BLE001
        print(f"bench: tunnel probes failed: {exc}", file=sys.stderr)
        degraded.append("tunnel_probes")
        rtt_s = enq_s = h2d_s = None
        batch_bytes = 0

    # -- flash-attention win at long sequence (VERDICT r2 #4): autotuned
    # blocks, plus a REAL long-context model row (BERT seq-2048, flash)
    flash_ms = xla_ms = mflash_ms = mxla_ms = f8k_ms = x8k_ms = None
    flash_blocks = f8k_blocks = None
    bert2k_sec = None
    if not small and os.environ.get("BENCH_FLASH", "1") == "1":
        try:
            from tfk8s_tpu.ops.flash_attention import autotune_blocks, pick_blocks

            fseq = int(os.environ.get("BENCH_FLASH_SEQ", "2048"))
            tuned = autotune_blocks(fseq)
            # no tuned winner -> the static divisibility-safe choice; if
            # even that is None (seq not a 128 multiple) SKIP the flash
            # rows instead of aborting the whole bench on the kernel's
            # divisibility assert
            flash_blocks = tuned[:2] if tuned else pick_blocks(fseq)
            if flash_blocks is not None:
                flash_ms, xla_ms = _flash_speedup(seq=fseq, blocks=flash_blocks)
                # the mask-capable path (BERT/T5 key padding) measured too
                mflash_ms, mxla_ms = _flash_speedup(
                    seq=fseq, blocks=flash_blocks, masked=True
                )
        except Exception as exc:  # noqa: BLE001
            print(f"bench: flash section failed: {exc}", file=sys.stderr)
            degraded.append("flash")
            flash_ms = mflash_ms = None
        # long-context point: seq 8192 at b1/h4 — the regime flash exists
        # for (the XLA reference materializes a 1 GB [b,h,L,L] scores
        # buffer; flash stays O(L·d)). Degrades on its own.
        if os.environ.get("BENCH_FLASH_LONG", "1") == "1":
            try:
                from tfk8s_tpu.ops.flash_attention import pick_blocks as _pb

                # autotune AT the 8192 geometry (VERDICT r4 weak #1: r4
                # reused blocks tuned at 2048 — the one length where the
                # [L, L] buffer actually hurts was measured with a 4x
                # shorter length's winner). Candidates skewed to larger
                # tiles: at L=8192 the per-tile compute amortizes better
                # and the scores row is the VMEM pressure, not [bq, bk].
                l_tuned = autotune_blocks(
                    8192, batch=1, heads=4, iters=2,
                    candidates=[
                        (512, 512), (1024, 512), (1024, 1024),
                        (512, 1024), (256, 512),
                    ],
                )
                lblocks = l_tuned[:2] if l_tuned else _pb(8192)
                if lblocks is not None:
                    f8k_blocks = tuple(lblocks)
                    f8k_ms, x8k_ms = _flash_speedup(
                        seq=8192, iters=4, blocks=lblocks, b=1, h=4
                    )
            except Exception as exc:  # noqa: BLE001
                print(f"bench: flash seq-8192 row failed: {exc}", file=sys.stderr)
                degraded.append("flash_8k")
        if flash_blocks is not None and flash_ms is not None:
            # the model row degrades on its own — a failure here must not
            # discard the attention speedups already measured above
            try:
                bert2k_cfg = bert.base_config(max_len=2048)
                bert2k_task = bert.task_for_mesh(
                    mesh, cfg=bert2k_cfg, seq_len=2048,
                    batch_size=int(os.environ.get("BENCH_BERT2K_BATCH", "8")),
                )
                bert2k_sec, _bert2k_windows = _time_task(bert2k_task, mesh, 20)
            except Exception as exc:  # noqa: BLE001
                print(f"bench: bert2k row failed: {exc}", file=sys.stderr)
                degraded.append("bert2k")

    # -- serving shape: KV-cache greedy decode (models/gpt.py). Runs in
    # small mode too (rc coverage) but the gpt2-named keys are only
    # emitted at the FULL config — a tiny-config number published under
    # a gpt2 key would read as massive drift vs the baseline ------------
    gpt_ms_tok = gpt_tok_s = None
    gpt_windows: list = []
    if os.environ.get("BENCH_GPT_DECODE", "1") == "1":
        try:
            gpt_ms_tok, gpt_tok_s, gpt_windows = _gpt_decode_ms_per_token(small)
        except Exception as exc:  # noqa: BLE001
            print(f"bench: gpt decode row failed: {exc}", file=sys.stderr)
            degraded.append("gpt_decode")
    # serving-throughput shape: batch 32 (decode is bandwidth-bound, so
    # batching multiplies generated tok/s near-linearly until compute binds)
    gpt32_tok_s = None
    if not small and os.environ.get("BENCH_GPT_DECODE", "1") == "1":
        try:
            _ms32, gpt32_tok_s, _w32 = _gpt_decode_ms_per_token(
                small, batch=32
            )
        except Exception as exc:  # noqa: BLE001
            print(f"bench: gpt decode bs32 row failed: {exc}", file=sys.stderr)
            degraded.append("gpt_decode_bs32")

    # -- input pipeline: native record-reader throughput (host-side) -----
    recordio_block = None
    if os.environ.get("BENCH_RECORDIO", "1") == "1":
        try:
            recordio_block = _recordio_probe(small)
        except Exception as exc:  # noqa: BLE001
            print(f"bench: recordio probe failed: {exc}", file=sys.stderr)
            degraded.append("recordio")

    # -- image data plane: decode+augment pool images/s vs the input
    # budget the ResNet-50 headline implies (host-side, no chip) --------
    image_block = None
    if os.environ.get("BENCH_IMAGES", "1") == "1":
        try:
            image_block = _image_pipeline_probe(small)
        except Exception as exc:  # noqa: BLE001
            print(f"bench: image pipeline probe failed: {exc}", file=sys.stderr)
            degraded.append("images")

    # -- serving data plane: dynamic-batching model server, offered-QPS
    # sweep (host-side; the TPUServe runtime measured without the control
    # plane — the serve-controller e2e covers that half) -----------------
    serving_block = None
    if os.environ.get("BENCH_SERVING", "1") == "1":
        try:
            serving_block = _serving_probe(
                small, full=os.environ.get("BENCH_SERVING_FULL") == "1"
            )
        except Exception as exc:  # noqa: BLE001
            print(f"bench: serving probe failed: {exc}", file=sys.stderr)
            degraded.append("serving")

    # -- generative serving: continuous-batching decode loop vs the
    # slot-per-batch baseline, mixed prompt/output lengths (host-side) ---
    gen_serving_block = None
    if os.environ.get("BENCH_GEN_SERVING", "1") == "1":
        try:
            gen_serving_block = _gen_serving_probe(
                small, full=os.environ.get("BENCH_GEN_SERVING_FULL") == "1"
            )
        except Exception as exc:  # noqa: BLE001
            print(f"bench: gen serving probe failed: {exc}", file=sys.stderr)
            degraded.append("gen_serving")

    # -- gateway front door: the serving sweep through the wire plus the
    # multi-tenant fairness round (hermetic: real sockets, fake cluster) -
    gateway_block = None
    if os.environ.get("BENCH_GATEWAY", "1") == "1":
        try:
            gateway_block = _gateway_probe(
                small, full=os.environ.get("BENCH_GATEWAY_FULL") == "1"
            )
        except Exception as exc:  # noqa: BLE001
            print(f"bench: gateway probe failed: {exc}", file=sys.stderr)
            degraded.append("gateway")

    # -- serving chaos: seeded kill of 1-of-3 replicas mid-generation
    # under offered-QPS load (hermetic: real sockets, fake cluster) ------
    chaos_block = None
    if os.environ.get("BENCH_CHAOS", "1") == "1":
        try:
            chaos_block = _chaos_serving_probe(
                small, full=os.environ.get("BENCH_CHAOS_FULL") == "1"
            )
        except Exception as exc:  # noqa: BLE001
            print(f"bench: chaos serving probe failed: {exc}", file=sys.stderr)
            degraded.append("chaos_serving")

    # -- disaggregated serving: prefix-affinity re-prefill savings and
    # burst-isolated decode TPOT vs a shared pool (hermetic) -------------
    disagg_block = None
    if os.environ.get("BENCH_DISAGG", "1") == "1":
        try:
            disagg_block = _disagg_serving_probe(
                small, full=os.environ.get("BENCH_DISAGG_FULL") == "1"
            )
        except Exception as exc:  # noqa: BLE001
            print(
                f"bench: disagg serving probe failed: {exc}", file=sys.stderr
            )
            degraded.append("disagg_serving")

    # -- token scheduler: per-class p99 TPOT under a mixed-priority flood
    # (priority vs FIFO, page-spill preemption) and speculative decode
    # tokens/s with a chain-trained draft/target pair (host-side) --------
    sched_block = None
    if os.environ.get("BENCH_SCHED", "1") == "1":
        try:
            sched_block = _sched_probe(
                small, full=os.environ.get("BENCH_SCHED_FULL") == "1"
            )
        except Exception as exc:  # noqa: BLE001
            print(f"bench: sched probe failed: {exc}", file=sys.stderr)
            degraded.append("sched")

    # -- KV economy: tiered prefix residency (device -> host demote/
    # restore) re-prefill savings and directory-hinted peer-fetch TTFT
    # vs plain re-prefill (host-side, hermetic) --------------------------
    kv_block = None
    if os.environ.get("BENCH_KV_ECONOMY", "1") == "1":
        try:
            kv_block = _kv_economy_probe(
                small, full=os.environ.get("BENCH_KV_ECONOMY_FULL") == "1"
            )
        except Exception as exc:  # noqa: BLE001
            print(f"bench: kv economy probe failed: {exc}", file=sys.stderr)
            degraded.append("kv_economy")

    # -- elastic recovery: reclaim-notice -> resized-gang-training time
    # against the real controller + kubelet (hermetic, chip-free) --------
    recovery_block = None
    if os.environ.get("BENCH_RECOVERY", "1") == "1":
        try:
            recovery_block = _recovery_probe(
                small, full=os.environ.get("BENCH_RECOVERY_FULL") == "1"
            )
        except Exception as exc:  # noqa: BLE001
            print(f"bench: recovery probe failed: {exc}", file=sys.stderr)
            degraded.append("recovery")

    baseline_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    vs = 1.0
    baseline_note = {}
    # the baseline's documented measurement band (BENCH_BASELINE.json
    # "band", falling back to the round-2 recorded inter-run spread):
    # a vs_baseline inside it is measurement noise, outside it is signal
    band = [0.92, 1.08]
    if os.path.exists(baseline_path):
        try:
            prior = json.load(open(baseline_path))
            if prior.get("value"):
                vs = value / float(prior["value"])
                band = list(prior.get("band", band))
                # an apples-to-apples ratio needs matching config; flag a
                # mismatch rather than passing config drift off as a win
                pb = prior.get("extra", {}).get("resnet_batch_size")
                if pb is not None and pb != rn_task.batch_size:
                    baseline_note = {
                        "baseline_resnet_batch_size": pb,
                        "baseline_config_mismatch": True,
                    }
        except (ValueError, KeyError):
            pass

    # -- committed roofline block (tools/roofline.py; VERDICT r3 next #2):
    # the platform envelope the memory-bound headline claim is judged
    # against, re-measured every bench run so drift is visible -----------
    roofline_block = None
    if os.environ.get("BENCH_ROOFLINE", "1") == "1":
        try:
            from tools import roofline

            roofline_block = roofline.run_all(small=small)
            roofline_block["resnet_step_ms"] = round(sec_per_step * 1000, 1)
        except Exception as exc:  # noqa: BLE001
            print(f"bench: roofline block failed: {exc}", file=sys.stderr)
            degraded.append("roofline")

    # -- control-plane block (tools/control_plane_bench.py; VERDICT r4
    # next #5): the reference's own hot loop — informer → workqueue →
    # reconcile — measured hermetically on CPU (no tunnel, no chip) ------
    control_plane_block = None
    if os.environ.get("BENCH_CONTROL_PLANE", "1") == "1":
        try:
            from tools import control_plane_bench

            control_plane_block = control_plane_bench.run_all(small=small)
        except Exception as exc:  # noqa: BLE001
            print(f"bench: control-plane block failed: {exc}", file=sys.stderr)
            degraded.append("control_plane")

    # Absolute efficiency (VERDICT r2 next #1): MFU from model FLOPs and
    # the chip's bf16 spec — drift-proof, unlike the ±5% vs_baseline
    # ratio on this shared chip. ResNet-50@224 fwd ≈ 4.11 GFLOP/image,
    # train ≈ 3x fwd; BERT train ≈ 6 * params * tokens (110M params).
    # The constants describe the FULL configs on the v5e, so the fields
    # are omitted in BENCH_SMALL mode (tiny models, other backend).
    PEAK_BF16 = 197e12  # v5e
    mfu_fields = {}
    if not small:
        resnet_mfu = (
            rn_task.batch_size * 3 * 4.11e9 / sec_per_step
        ) / PEAK_BF16
        bert_tokens = bert_task.batch_size * bert_seq
        bert_mfu = (6 * 110e6 * bert_tokens / bert_sec) / PEAK_BF16
        mfu_fields = {
            "resnet_mfu": round(resnet_mfu, 4),
            "bert_mfu": round(bert_mfu, 4),
        }

    detail = {
                "metric": "resnet50_images_per_sec_per_chip",
                "value": round(value, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(vs, 4),
                "extra": {
                    **baseline_note,
                    **mfu_fields,
                    "bert_base_mlm_step_time_ms": round(bert_sec * 1000, 3),
                    **(
                        {
                            "bert_fit_step_time_ms": round(fit_sec * 1000, 3),
                            "bert_fit_vs_scanned": round(fit_sec / bert_sec, 3),
                            "fit_gap_ms_per_step": round(
                                (fit_sec - bert_sec) * 1000, 3
                            ),
                        }
                        if fit_sec is not None
                        else {}
                    ),
                    **(
                        {
                            "bert_fit_scan8_step_time_ms": round(
                                fit8_sec * 1000, 3
                            ),
                            "bert_fit_scan8_vs_scanned": round(
                                fit8_sec / bert_sec, 3
                            ),
                        }
                        if fit8_sec is not None
                        else {}
                    ),
                    # the measured tunnel costs that bound the fit gap
                    # (per step the product loop pays one async dispatch
                    # enqueue + one batch H2D the scanned bench does not;
                    # the sync round trip is what any mid-loop scalar
                    # fetch would cost — why fit batches its fetches)
                    **(
                        {
                            "tunnel_sync_roundtrip_ms": round(rtt_s * 1000, 3),
                            "tunnel_dispatch_enqueue_ms": round(enq_s * 1000, 3),
                            "tunnel_h2d_ms_per_batch": round(h2d_s * 1000, 3),
                            # rate only when the transfer was resolvable
                            # above the RTT floor (h2d is rtt-subtracted
                            # and clamped at 0 — a 0 would divide into an
                            # absurd figure)
                            **(
                                {"tunnel_h2d_mbps": round(
                                    batch_bytes / h2d_s / 1e6, 1
                                )}
                                if h2d_s > 1e-6
                                else {}
                            ),
                        }
                        if rtt_s is not None
                        else {}
                    ),
                    **({"degraded_sections": degraded} if degraded else {}),
                    "bert_batch_size": bert_task.batch_size,
                    "bert_seq_len": bert_seq,
                    "resnet_batch_size": rn_task.batch_size,
                    "n_chips": n_chips,
                    **(
                        {
                            "gpt2_decode_ms_per_token": round(gpt_ms_tok, 3),
                            "gpt2_decode_tokens_per_sec": round(gpt_tok_s, 1),
                            "gpt2_decode_param_dtype": "bfloat16",
                        }
                        if gpt_ms_tok is not None and not small
                        else {}
                    ),
                    **(
                        {
                            "gpt2_decode_bs32_tokens_per_sec": round(
                                gpt32_tok_s, 1
                            ),
                        }
                        if gpt32_tok_s is not None and not small
                        else {}
                    ),
                    # self-described noise floor (VERDICT r3 next #9)
                    "noise": {
                        **(
                            {
                                "gpt_decode_step_windows_ms": [
                                    round(w, 3) for w in gpt_windows
                                ]
                            }
                            if gpt_windows and not small
                            else {}
                        ),
                        "windows_per_metric": _WINDOWS,
                        **(
                            {"fit_step_windows_ms": [
                                round(w * 1000, 2) for w in fit_windows
                            ]}
                            if fit_windows
                            else {}
                        ),
                        **(
                            {"fit_scan8_step_windows_ms": [
                                round(w * 1000, 2) for w in fit8_windows
                            ]}
                            if fit8_windows
                            else {}
                        ),
                        "resnet_step_windows_ms": [
                            round(w * 1000, 2) for w in rn_windows
                        ],
                        "bert_step_windows_ms": [
                            round(w * 1000, 2) for w in bert_windows
                        ],
                        "baseline_band": band,
                        "vs_baseline_outside_band": not (
                            band[0] <= vs <= band[1]
                        ),
                    },
                    **({"roofline": roofline_block} if roofline_block else {}),
                    **(
                        {"control_plane": control_plane_block}
                        if control_plane_block
                        else {}
                    ),
                    **({"recordio": recordio_block} if recordio_block else {}),
                    **({"images": image_block} if image_block else {}),
                    **({"serving": serving_block} if serving_block else {}),
                    **(
                        {"gen_serving": gen_serving_block}
                        if gen_serving_block else {}
                    ),
                    **({"gateway": gateway_block} if gateway_block else {}),
                    **(
                        {"chaos_serving": chaos_block}
                        if chaos_block else {}
                    ),
                    **(
                        {"disagg_serving": disagg_block}
                        if disagg_block else {}
                    ),
                    **({"sched": sched_block} if sched_block else {}),
                    **({"kv_economy": kv_block} if kv_block else {}),
                    **({"recovery": recovery_block} if recovery_block else {}),
                    **(
                        {
                            "flash_attn_ms_seq2048": round(flash_ms, 3),
                            "xla_attn_ms_seq2048": round(xla_ms, 3),
                            "flash_attn_speedup": round(xla_ms / flash_ms, 3),
                            "flash_attn_masked_ms": round(mflash_ms, 3),
                            "xla_attn_masked_ms": round(mxla_ms, 3),
                            "flash_attn_masked_speedup": round(
                                mxla_ms / mflash_ms, 3
                            ),
                            "flash_blocks": list(flash_blocks or ()),
                        }
                        if flash_ms
                        else {}
                    ),
                    # the seq-8192 row stands on its own — a degraded
                    # seq-2048 section must not drop it from the artifact
                    **(
                        {
                            "flash_attn_seq8192_ms": round(f8k_ms, 3),
                            "xla_attn_seq8192_ms": round(x8k_ms, 3),
                            "flash_attn_seq8192_speedup": round(
                                x8k_ms / f8k_ms, 3
                            ),
                            "flash_blocks_seq8192": list(f8k_blocks),
                        }
                        if f8k_ms
                        else {}
                    ),
                    **(
                        {
                            "bert_seq2048_flash_step_time_ms": round(
                                bert2k_sec * 1000, 3
                            ),
                            "bert_seq2048_batch_size": int(
                                os.environ.get("BENCH_BERT2K_BATCH", "8")
                            ),
                        }
                        if bert2k_sec
                        else {}
                    ),
                },
            }

    # -- driver artifact contract (VERDICT r5 next #1): the FINAL stdout
    # line is one compact headline JSON that fits the driver's tail
    # capture; the full measurement record goes to a committed
    # BENCH_DETAIL_*.json the headline names. Round 5 broke here — the
    # detail outgrew the 2,000-char tail and the archived artifact lost
    # its headline keys entirely.
    tag = os.environ.get("BENCH_TAG", "local")
    detail_name = f"BENCH_DETAIL_{tag}.json"
    detail_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), detail_name)
    try:
        with open(detail_path, "w") as f:
            json.dump(detail, f, indent=1, sort_keys=True)
            f.write("\n")
    except OSError as exc:  # read-only checkout: headline still stands
        print(f"bench: could not write {detail_name}: {exc}", file=sys.stderr)
        detail_name = None

    print(
        build_headline(
            detail, image_block, detail_name, serving_block, recovery_block,
            gen_serving_block, gateway_block, chaos_block, disagg_block,
            sched_block, kv_block,
        )
    )


# The driver-artifact contract (VERDICT r5 next #1), enforced by the
# tier-1 test tests/test_bench_headline.py: ONE JSON line, at most this
# many characters — round 5's full record outgrew the driver's 2,000-char
# tail capture and the archived artifact lost its headline keys entirely.
HEADLINE_MAX_CHARS = 1800


def build_headline(
    detail: dict, image_block, detail_name, serving_block=None,
    recovery_block=None, gen_serving_block=None, gateway_block=None,
    chaos_block=None, disagg_block=None, sched_block=None, kv_block=None,
) -> str:
    """Assemble the final-stdout headline line from the full detail
    record: the fixed key set, the image-decode and serving rows when
    present, and a graceful degrade order that drops optional keys until
    the line fits HEADLINE_MAX_CHARS — the ceiling holds even if a future
    key grows."""
    extra = detail["extra"]
    headline_extra = {
        k: extra[k]
        for k in (
            "bert_base_mlm_step_time_ms",
            "resnet_mfu",
            "bert_mfu",
            "resnet_batch_size",
            "bert_batch_size",
            "bert_seq_len",
            "n_chips",
            "gpt2_decode_tokens_per_sec",
            "flash_attn_speedup",
            "degraded_sections",
            "baseline_config_mismatch",
        )
        if k in extra
    }
    if image_block:
        # the decode rows ride the headline: delivered img/s vs the
        # ResNet input budget, plus the per-worker backend pair —
        # img_per_sec_native appears ONLY when the native backend
        # actually ran (the driver's acceptance key)
        headline_extra.update(
            {
                k: image_block[k]
                for k in (
                    "image_decode_images_per_sec",
                    "image_decode_mbps_decoded",
                    "image_decode_workers",
                    "image_backend",
                    "image_px",
                    "image_budget_images_per_sec",
                    "image_meets_budget",
                    "img_per_sec_pil",
                    "img_per_sec_native",
                    "image_native_vs_pil",
                )
                if k in image_block
            }
        )
    if serving_block:
        # the serving rows ride the headline: achieved QPS at the best
        # sweep point, its p50/p99, and the mean batch occupancy — the
        # driver's acceptance keys for the serving block
        headline_extra.update(
            {
                k: serving_block[k]
                for k in (
                    "serving_qps",
                    "serving_p50_ms",
                    "serving_p99_ms",
                    "serving_batch_occupancy",
                    "serving_model",
                )
                if k in serving_block
            }
        )
    if gen_serving_block:
        # the continuous-batching rows ride the headline: useful generated
        # tokens/s under the mixed-length workload, its p99 TPOT, and the
        # speedup over the slot-per-batch baseline — the driver's
        # acceptance keys for the generative serving arm
        headline_extra.update(
            {
                k: gen_serving_block[k]
                for k in (
                    "gen_tokens_per_s",
                    "tpot_p99_ms",
                    "ttft_p99_ms",
                    "gen_speedup_vs_batch",
                    "gen_tokens_per_s_baseline",
                )
                if k in gen_serving_block
            }
        )
    if gateway_block:
        # the gateway rows ride the headline: wire QPS at the best sweep
        # point, its p99, the wire/in-process efficiency, and the
        # multi-tenant fairness ratio — the driver's acceptance keys for
        # the front-door arm
        headline_extra.update(
            {
                k: gateway_block[k]
                for k in (
                    "gateway_qps",
                    "gateway_p99_ms",
                    "gateway_wire_efficiency",
                    "gateway_trace_overhead",
                    "gateway_fairness_ratio",
                )
                if k in gateway_block
            }
        )
    if chaos_block:
        # the serving-chaos rows ride the headline: requests lost to the
        # seeded mid-generation kill (the acceptance key — must be 0),
        # the p99 under chaos, and how fast the health machinery stopped
        # routing to the corpse
        headline_extra.update(
            {
                k: chaos_block[k]
                for k in (
                    "chaos_failed_requests",
                    "chaos_p99_ms",
                    "ejection_time_ms",
                )
                if k in chaos_block
            }
        )
    if disagg_block:
        # the disaggregation rows ride the headline: the fraction of
        # re-prefill tokens prefix-affinity saved over depth-only
        # scatter, and the live streams' p99 TPOT under a prompt burst
        # for the split pools vs the shared-pool baseline — the driver's
        # acceptance keys for the disagg arm
        headline_extra.update(
            {
                k: disagg_block[k]
                for k in (
                    "affinity_reprefill_saved",
                    "disagg_tpot_p99_ms",
                    "shared_tpot_p99_ms",
                    "disagg_tpot_win",
                )
                if k in disagg_block
            }
        )
    if sched_block:
        # the token-scheduler rows ride the headline: the interactive
        # class's p99 TPOT under the priority scheduler vs FIFO (the
        # latency claim), the preemption count that bought it, the
        # priority arm's aggregate tokens/s (the no-throughput-regression
        # claim), and the speculative speedup + realized accept ratio —
        # the driver's acceptance keys for the scheduler arm
        headline_extra.update(
            {
                k: sched_block[k]
                for k in (
                    "sched_hi_tpot_p99_ms",
                    "sched_hi_tpot_p99_ms_fifo",
                    "sched_preemptions",
                    "sched_tokens_per_s",
                    "sched_spec_speedup",
                    "sched_spec_accept_ratio",
                )
                if k in sched_block
            }
        )
    if kv_block:
        # the KV-economy rows ride the headline: the re-prefill fraction
        # the host tier saved over the untied device pool (the driver's
        # acceptance key, judged against the PR 14 affinity baseline),
        # the restore/fetch TTFT p99s, and the re-prefill p99 the peer
        # fetch is judged against
        headline_extra.update(
            {
                k: kv_block[k]
                for k in (
                    "kv_reprefill_saved",
                    "kv_host_restore_p99_ms",
                    "kv_peer_fetch_p99_ms",
                    "kv_peer_reprefill_p99_ms",
                )
                if k in kv_block
            }
        )
    if recovery_block:
        # the elastic-recovery rows ride the headline: seconds from a
        # reclaim notice to the RESIZED gang's first post-resize optimizer
        # step — the driver's acceptance keys for the recovery arm
        headline_extra.update(
            {
                k: recovery_block[k]
                for k in (
                    "recovery_p50_s",
                    "recovery_p99_s",
                    "recovery_backoff_burned",
                )
                if k in recovery_block
            }
        )
    headline = {
        "metric": detail["metric"],
        "value": detail["value"],
        "unit": detail["unit"],
        "vs_baseline": detail["vs_baseline"],
        **({"detail": detail_name} if detail_name else {}),
        "extra": headline_extra,
    }
    line = json.dumps(headline)
    for drop in (
        "flash_attn_speedup", "gpt2_decode_tokens_per_sec", "bert_seq_len",
        "bert_batch_size", "image_px", "image_decode_workers",
        "image_native_vs_pil", "img_per_sec_pil", "image_backend",
        "serving_model", "serving_p50_ms", "serving_batch_occupancy",
        "recovery_backoff_burned",
        "gen_tokens_per_s_baseline", "gen_speedup_vs_batch",
        "gateway_trace_overhead",
        "gateway_wire_efficiency", "gateway_p99_ms",
        "chaos_p99_ms", "ejection_time_ms",
        "sched_hi_tpot_p99_ms_fifo", "sched_preemptions",
        "kv_peer_reprefill_p99_ms", "kv_host_restore_p99_ms",
        "disagg_tpot_win", "shared_tpot_p99_ms",
        "bert_mfu", "resnet_mfu",
        "image_decode_mbps_decoded", "image_budget_images_per_sec",
        "image_meets_budget", "img_per_sec_native",
        "serving_p99_ms", "serving_qps",
        "gateway_fairness_ratio", "gateway_qps",
        "chaos_failed_requests",
        "ttft_p99_ms",
        "sched_spec_accept_ratio", "sched_spec_speedup",
        "sched_tokens_per_s", "sched_hi_tpot_p99_ms",
        "kv_peer_fetch_p99_ms", "kv_reprefill_saved",
        "tpot_p99_ms", "gen_tokens_per_s",
        "disagg_tpot_p99_ms", "affinity_reprefill_saved",
        "recovery_p99_s", "recovery_p50_s",
        "image_decode_images_per_sec", "bert_base_mlm_step_time_ms",
    ):
        if len(line) <= HEADLINE_MAX_CHARS:
            break
        headline["extra"].pop(drop, None)
        line = json.dumps(headline)
    return line


if __name__ == "__main__":
    main()
