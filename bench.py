"""Headline benchmark: ResNet-50 images/sec/chip (BASELINE.json "metric").

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (`BASELINE.json "published": {}`,
SURVEY.md §6), so ``vs_baseline`` compares against the last recorded run
of *this* repo (BENCH_BASELINE.json, committed after each round) — 1.0 on
the first measurement.

Runs on whatever backend JAX finds: the driver runs it on the one real
TPU chip; set BENCH_SMALL=1 for a seconds-scale CPU smoke run.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tfk8s_tpu.models import resnet
    from tfk8s_tpu.parallel.mesh import make_mesh
    from tfk8s_tpu.runtime.train import TrainConfig, Trainer

    small = os.environ.get("BENCH_SMALL") == "1"
    if small:
        task = resnet.make_task(
            depth=18, num_classes=8, image_size=32, batch_size=8, width=8
        )
        steps, warmup = 8, 3
    else:
        task = resnet.make_task(
            depth=50,
            num_classes=1000,
            image_size=224,
            batch_size=int(os.environ.get("BENCH_BATCH", "128")),
        )
        steps, warmup = 30, 10

    n_chips = jax.device_count()
    mesh = make_mesh(data=n_chips)
    trainer = Trainer(task, TrainConfig(steps=steps, learning_rate=1e-3), mesh)
    state = trainer.init_state()
    shardings = trainer.batch_shardings
    rng = np.random.default_rng(0)
    # Pre-stage batches on device: the benchmark measures the training
    # step (the thing the metric is defined over), not the synthetic-data
    # host pipeline / tunnel transfer. All timed steps run inside ONE
    # jitted lax.scan — a single dispatch with a strict device-side
    # dependency chain, immune to async-dispatch timing artifacts.
    import jax.numpy as jnp

    from jax.sharding import NamedSharding, PartitionSpec as P

    host = [task.make_batch(rng, task.batch_size) for _ in range(4)]
    stacked = jax.device_put(
        jax.tree_util.tree_map(lambda *xs: np.stack(xs), *host),
        jax.tree_util.tree_map(
            lambda s: NamedSharding(s.mesh, P(None, *s.spec)), shardings
        ),
    )

    def run_n(state, n):
        def body(s, i):
            batch = jax.tree_util.tree_map(lambda x: x[i % 4], stacked)
            s, metrics = trainer._step_fn(s, batch, jax.random.fold_in(jax.random.key(0), i))
            return s, metrics["loss"]
        return jax.lax.scan(body, state, jnp.arange(n))

    run = jax.jit(run_n, static_argnums=1)
    state, losses = run(state, warmup)  # compile + warm
    jax.block_until_ready(losses)

    t0 = time.perf_counter()
    state, losses = run(state, steps)
    jax.block_until_ready(losses)
    dt = time.perf_counter() - t0

    images_per_sec = task.batch_size * steps / dt
    value = images_per_sec / n_chips

    baseline_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    vs = 1.0
    if os.path.exists(baseline_path):
        try:
            prior = json.load(open(baseline_path))
            if prior.get("value"):
                vs = value / float(prior["value"])
        except (ValueError, KeyError):
            pass

    print(
        json.dumps(
            {
                "metric": "resnet50_images_per_sec_per_chip",
                "value": round(value, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(vs, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
